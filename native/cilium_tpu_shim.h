/*
 * cilium-tpu datapath shim — public C ABI.
 *
 * The native client of the verdict-service seam: the counterpart of the
 * reference's Envoy-side consumer of libcilium.so (reference:
 * envoy/cilium_proxylib.cc dlopen + GoFilter::Instance::OnIO;
 * proxylib/libcilium.h cgo surface).  Where the reference crosses a cgo
 * boundary in-process, this shim crosses a unix-socket wire boundary to
 * the TPU verdict service (cilium_tpu/sidecar/service.py), buffering
 * per-connection bytes and applying returned filter ops with the OnIO
 * byte-accounting contract.
 *
 * Op/result enums and the FilterOp struct are numerically and
 * layout-identical to the reference ABI (reference:
 * proxylib/proxylib/types.h) so a consumer written against that contract
 * can link against this shim unchanged.
 *
 * Transport: this shim speaks the SOCKET rung of the transport seam
 * (cilium_tpu/sidecar/transport.py).  The service also offers a
 * shared-memory fast path (MSG_SHM_* 19-23: ring attach/doorbell/
 * credit), negotiated per session and never required — a client that
 * does not attach rings is served on the socket exactly as before, and
 * unknown frame types are skipped by this shim's recv loops, so both
 * client kinds coexist on one service.
 */

#ifndef CILIUM_TPU_SHIM_H
#define CILIUM_TPU_SHIM_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  CT_FILTEROP_MORE = 0,
  CT_FILTEROP_PASS = 1,
  CT_FILTEROP_DROP = 2,
  CT_FILTEROP_INJECT = 3,
  CT_FILTEROP_ERROR = 4,
} CiliumTpuFilterOpType;

typedef struct {
  uint64_t op;     /* CiliumTpuFilterOpType */
  int64_t n_bytes; /* > 0 */
} CiliumTpuFilterOp;

typedef enum {
  CT_FILTER_OK = 0,
  CT_FILTER_POLICY_DROP = 1,
  CT_FILTER_PARSER_ERROR = 2,
  CT_FILTER_UNKNOWN_PARSER = 3,
  CT_FILTER_UNKNOWN_CONNECTION = 4,
  CT_FILTER_INVALID_ADDRESS = 5,
  CT_FILTER_INVALID_INSTANCE = 6,
  CT_FILTER_UNKNOWN_ERROR = 7,
} CiliumTpuFilterResult;

/* Connect to the verdict service at socket_path and open a module
 * (the OpenModule analog).  Returns a module handle, 0 on error. */
uint64_t cilium_tpu_open(const char *socket_path, uint8_t debug);

/* Close the module and its socket (the CloseModule analog). */
void cilium_tpu_close_module(uint64_t module);

/* Push a JSON-encoded NetworkPolicy list (the NPDS push analog).
 * Returns a CiliumTpuFilterResult; non-OK leaves active policy
 * untouched. */
uint32_t cilium_tpu_policy_update_json(uint64_t module, const char *json,
                                       size_t len);

/* Register a connection (the OnNewConnection analog). */
uint32_t cilium_tpu_on_new_connection(uint64_t module, const char *proto,
                                      uint64_t conn_id, uint8_t ingress,
                                      uint32_t src_id, uint32_t dst_id,
                                      const char *src_addr,
                                      const char *dst_addr,
                                      const char *policy_name);

/* Ship new bytes for one direction and receive filter ops (the OnData
 * analog).  On entry *n_ops is the ops array capacity and
 * *inject_orig_len / *inject_reply_len the inject buffer capacities; on
 * return they hold the produced counts.  Ops beyond the capacity are
 * retained shim-side and returned by the next call (continuation). */
uint32_t cilium_tpu_on_data(uint64_t module, uint64_t conn_id, uint8_t reply,
                            uint8_t end_stream, const uint8_t *data,
                            int64_t len, CiliumTpuFilterOp *ops,
                            int32_t *n_ops, uint8_t *inject_orig,
                            int64_t *inject_orig_len, uint8_t *inject_reply,
                            int64_t *inject_reply_len);

/* Full datapath hot loop for one direction (the GoFilter::Instance::OnIO
 * analog, reference: envoy/cilium_proxylib.cc:125-214): feeds input,
 * applies pre-pass/pre-drop counters, outputs reverse-injected frames,
 * then applies returned ops to the retained buffer.  Forwardable bytes
 * are written to output (capacity out_cap); *out_len receives the
 * count.  Returns a CiliumTpuFilterResult. */
uint32_t cilium_tpu_on_io(uint64_t module, uint64_t conn_id, uint8_t reply,
                          uint8_t end_stream, const uint8_t *input,
                          int64_t in_len, uint8_t *output, int64_t out_cap,
                          int64_t *out_len);

/* Deregister a connection (the Close analog). */
void cilium_tpu_close_connection(uint64_t module, uint64_t conn_id);

/* ---- access log client (reference: envoy/accesslog.cc) ---------------
 *
 * Per-request log records written over a unix socket to the agent's
 * access-log server (cilium_tpu/accesslog/server.py; framing: 4-byte
 * big-endian length + JSON LogRecord).  The client reconnects once per
 * send on failure, mirroring the reference's TryConnect-per-Log. */

/* Returns an accesslog handle, 0 on error (the path may not exist yet;
 * connection is (re)attempted per send). */
uint64_t cilium_tpu_accesslog_open(const char *socket_path);

void cilium_tpu_accesslog_close(uint64_t handle);

/* Send one pre-encoded JSON LogRecord. Returns 1 on success. */
uint32_t cilium_tpu_accesslog_send_json(uint64_t handle, const char *json,
                                        size_t len);

/* Build + send one verdict record (entry_type: 0 request forwarded,
 * 2 denied — matching accesslog/record.py's verdict strings). */
uint32_t cilium_tpu_accesslog_log_verdict(
    uint64_t handle, uint8_t denied, uint8_t ingress, uint32_t src_id,
    uint32_t dst_id, const char *src_addr, const char *dst_addr,
    const char *proto, const char *info);

/* Attach an accesslog to a module: cilium_tpu_on_io then emits one
 * record per applied PASS/DROP op group (the reference's per-request
 * C++ access logging; pass 0 to detach). */
void cilium_tpu_set_accesslog(uint64_t module, uint64_t accesslog);

/* ---- proxymap reader (reference: envoy/bpf.cc + envoy/proxymap.cc +
 * envoy/cilium_bpf_metadata.cc) -----------------------------------------
 *
 * Original-destination recovery for redirected connections: the
 * datapath writes proxymap snapshots to a file (the pinned-BPF-map
 * analog; cilium_tpu/maps/proxymap.py ProxyMap.save), and the native
 * proxy side opens + queries it at connection accept. */

/* Open (and load) a proxymap snapshot file. Returns a handle, 0 on
 * error. */
uint64_t cilium_tpu_proxymap_open(const char *path);

/* Re-read the snapshot if the file changed. Returns entry count, or
 * -1 on read failure (previous snapshot stays active). */
int64_t cilium_tpu_proxymap_refresh(uint64_t handle);

/* Look up the proxied 5-tuple (key fields as the datapath wrote them:
 * source perspective, dport = local proxy port).  On hit fills
 * orig_daddr/orig_dport/identity and returns 1. */
uint32_t cilium_tpu_proxymap_lookup(uint64_t handle, uint32_t saddr,
                                    uint32_t daddr, uint16_t sport,
                                    uint16_t dport, uint8_t proto,
                                    uint32_t *orig_daddr,
                                    uint32_t *orig_dport,
                                    uint32_t *identity);

void cilium_tpu_proxymap_close(uint64_t handle);

/* ---- host map (reference: envoy/cilium_host_map.cc PolicyHostMap) ----
 *
 * IP -> security-identity longest-prefix lookup inside the datapath
 * process, fed by ipcache snapshots
 * (cilium_tpu/maps/ipcache.py IpcacheMap.save). */

uint64_t cilium_tpu_hostmap_open(const char *path);

/* Re-read if the snapshot changed; returns entry count or -1. */
int64_t cilium_tpu_hostmap_refresh(uint64_t handle);

/* Longest-prefix match for addr (host byte order).  On hit fills
 * identity (and tunnel_endpoint if non-NULL) and returns the matched
 * prefix length + 1; returns 0 on miss. */
uint32_t cilium_tpu_hostmap_lookup(uint64_t handle, uint32_t addr,
                                   uint32_t *identity,
                                   uint32_t *tunnel_endpoint);

void cilium_tpu_hostmap_close(uint64_t handle);

/* ---- accept-path composition (reference: envoy/cilium_bpf_metadata.cc
 * onAccept + envoy/cilium_network_filter.cc onNewConnection) ----------
 *
 * One call for the datapath's connection-accept sequence: recover the
 * original destination + source identity for the redirected 5-tuple
 * from the proxymap, resolve identities via the host map (proxymap
 * identity wins for the source; misses fall back to the host map, then
 * to the reserved world identity), and register the connection with
 * the verdict service.  Returns the registration's
 * CiliumTpuFilterResult; on success fills orig_daddr/orig_dport/
 * src_id/dst_id.  Addresses are host byte order. */
uint32_t cilium_tpu_accept(uint64_t module, uint64_t proxymap,
                           uint64_t hostmap, const char *l7_proto,
                           uint64_t conn_id, uint8_t ingress,
                           uint32_t saddr, uint32_t daddr, uint16_t sport,
                           uint16_t dport, uint8_t proto_num,
                           const char *policy_name, uint32_t *orig_daddr,
                           uint32_t *orig_dport, uint32_t *src_id,
                           uint32_t *dst_id);

#ifdef __cplusplus
}
#endif

#endif /* CILIUM_TPU_SHIM_H */
