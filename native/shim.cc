// cilium-tpu datapath shim implementation.
//
// Native client of the verdict-service wire protocol
// (cilium_tpu/sidecar/wire.py).  Mirrors the role of the reference's
// Envoy-side GoFilter (reference: envoy/cilium_proxylib.cc): per-module
// socket, per-connection retained buffers and inject slices, and the
// OnIO byte-accounting loop applying PASS/DROP/INJECT/MORE ops.
//
// Threading: one mutex per module serializes socket round trips; a
// global registry mutex guards the handle tables.  Connections follow
// the reference's assumption of single-threaded access per connection.

#include "cilium_tpu_shim.h"

#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

constexpr uint16_t kMagic = 0xC17A;
constexpr uint16_t kMsgOpenModule = 1;
constexpr uint16_t kMsgModuleId = 2;
constexpr uint16_t kMsgNewConnection = 3;
constexpr uint16_t kMsgConnResult = 4;
constexpr uint16_t kMsgDataBatch = 5;
constexpr uint16_t kMsgVerdictBatch = 6;
constexpr uint16_t kMsgClose = 7;
constexpr uint16_t kMsgPolicyUpdate = 8;
constexpr uint16_t kMsgAck = 9;

struct Direction {
  std::string buffer;       // retained, not-yet-verdicted input
  int64_t pass_bytes = 0;   // verdicted PASS beyond buffered input
  int64_t drop_bytes = 0;   // verdicted DROP beyond buffered input
  int64_t need_bytes = 0;   // parser's MORE threshold (informational)
  std::string inject;       // per-direction inject slice
};

struct Connection {
  Direction dirs[2];  // [0]=orig/request, [1]=reply
  // Ops produced by the service but not yet handed to the caller
  // (cilium_tpu_on_data continuation when the caller's array is small).
  std::deque<CiliumTpuFilterOp> pending_ops[2];
};

struct Module {
  int fd = -1;
  uint64_t module_id = 0;
  uint64_t next_seq = 1;
  std::mutex io_mutex;
  // Guards the conns map itself (insert/erase/find from different
  // threads); per-connection state still follows the reference's
  // single-thread-per-connection contract (proxylib/libcilium.h).
  std::mutex conns_mutex;
  std::map<uint64_t, std::unique_ptr<Connection>> conns;

  Connection *find_conn(uint64_t conn_id) {
    std::lock_guard<std::mutex> lk(conns_mutex);
    auto it = conns.find(conn_id);
    return it == conns.end() ? nullptr : it->second.get();
  }
};

std::mutex g_registry_mutex;
std::map<uint64_t, std::unique_ptr<Module>> g_modules;
uint64_t g_next_handle = 1;

Module *find_module(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_registry_mutex);
  auto it = g_modules.find(handle);
  return it == g_modules.end() ? nullptr : it->second.get();
}

// --- low-level wire I/O ---------------------------------------------------

bool send_all(int fd, const void *buf, size_t n) {
  const char *p = static_cast<const char *>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_msg(int fd, uint16_t type, const std::string &payload) {
  char hdr[8];
  uint16_t magic = kMagic;
  uint32_t len = static_cast<uint32_t>(payload.size());
  memcpy(hdr, &magic, 2);
  memcpy(hdr + 2, &type, 2);
  memcpy(hdr + 4, &len, 4);
  return send_all(fd, hdr, 8) &&
         (payload.empty() || send_all(fd, payload.data(), payload.size()));
}

bool recv_msg(int fd, uint16_t *type, std::string *payload) {
  char hdr[8];
  if (!recv_all(fd, hdr, 8)) return false;
  uint16_t magic;
  uint32_t len;
  memcpy(&magic, hdr, 2);
  memcpy(type, hdr + 2, 2);
  memcpy(&len, hdr + 4, 4);
  if (magic != kMagic) return false;
  payload->resize(len);
  return len == 0 || recv_all(fd, &(*payload)[0], len);
}

template <typename T>
void put(std::string *out, T v) {
  out->append(reinterpret_cast<const char *>(&v), sizeof(T));
}

void put_str(std::string *out, const char *s) {
  uint16_t n = s ? static_cast<uint16_t>(strlen(s)) : 0;
  put<uint16_t>(out, n);
  if (n) out->append(s, n);
}

template <typename T>
T get(const std::string &buf, size_t *off) {
  T v;
  memcpy(&v, buf.data() + *off, sizeof(T));
  *off += sizeof(T);
  return v;
}

// One parsed verdict entry.
struct VerdictEntry {
  uint64_t conn_id;
  uint32_t result;
  std::vector<CiliumTpuFilterOp> ops;
  std::string inject_orig;
  std::string inject_reply;
};

// Bounds-checked parse: the wire peer is a trust boundary — a
// truncated or corrupt payload must fail the message, never read out
// of bounds.
bool parse_verdict_batch(const std::string &p, uint64_t *seq,
                         std::vector<VerdictEntry> *entries) {
  size_t off = 0;
  auto need = [&](size_t k) { return p.size() - off >= k; };
  if (!need(12)) return false;
  *seq = get<uint64_t>(p, &off);
  uint32_t n = get<uint32_t>(p, &off);
  if (n > (1u << 20)) return false;  // implausible entry count
  if (!need(static_cast<size_t>(n) * (8 + 4 * 4))) return false;
  std::vector<uint64_t> conn_ids(n);
  std::vector<uint32_t> results(n), op_counts(n), inj_o(n), inj_r(n);
  for (uint32_t i = 0; i < n; i++) conn_ids[i] = get<uint64_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) results[i] = get<uint32_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) op_counts[i] = get<uint32_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) inj_o[i] = get<uint32_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) inj_r[i] = get<uint32_t>(p, &off);
  entries->resize(n);
  for (uint32_t i = 0; i < n; i++) {
    VerdictEntry &e = (*entries)[i];
    e.conn_id = conn_ids[i];
    e.result = results[i];
    if (op_counts[i] > (1u << 16) ||
        !need(static_cast<size_t>(op_counts[i]) * 16))
      return false;
    e.ops.resize(op_counts[i]);
    for (uint32_t k = 0; k < op_counts[i]; k++) {
      e.ops[k].op = get<uint64_t>(p, &off);
      e.ops[k].n_bytes = get<int64_t>(p, &off);
    }
  }
  for (uint32_t i = 0; i < n; i++) {
    VerdictEntry &e = (*entries)[i];
    if (!need(static_cast<size_t>(inj_o[i]) + inj_r[i])) return false;
    e.inject_orig.assign(p.data() + off, inj_o[i]);
    off += inj_o[i];
    e.inject_reply.assign(p.data() + off, inj_r[i]);
    off += inj_r[i];
  }
  return true;
}

// Synchronous round trip expecting a given reply type; caller holds
// the module io_mutex.
bool rpc(Module *m, uint16_t type, const std::string &payload,
         uint16_t want_type, std::string *reply) {
  if (!send_msg(m->fd, type, payload)) return false;
  uint16_t got;
  for (;;) {
    if (!recv_msg(m->fd, &got, reply)) return false;
    if (got == want_type) return true;
    // Unexpected interleaved message (shouldn't happen with serialized
    // round trips); skip it.
  }
}

// Ship new bytes for a connection/direction; parse verdict entries and
// append their ops/injects to the connection's pending queues.
uint32_t on_data_rpc(Module *m, Connection *c, uint64_t conn_id, bool reply,
                     bool end_stream, const uint8_t *data, int64_t len) {
  std::lock_guard<std::mutex> lk(m->io_mutex);
  uint64_t seq = m->next_seq++;
  std::string payload;
  put<uint64_t>(&payload, seq);
  put<uint32_t>(&payload, 1);
  put<uint64_t>(&payload, conn_id);
  uint8_t flags = (reply ? 1 : 0) | (end_stream ? 2 : 0);
  put<uint8_t>(&payload, flags);
  put<uint32_t>(&payload, static_cast<uint32_t>(len));
  if (len > 0) payload.append(reinterpret_cast<const char *>(data), len);

  std::string rp;
  if (!send_msg(m->fd, kMsgDataBatch, payload)) return CT_FILTER_UNKNOWN_ERROR;
  for (;;) {
    uint16_t got;
    if (!recv_msg(m->fd, &got, &rp)) return CT_FILTER_UNKNOWN_ERROR;
    if (got != kMsgVerdictBatch) continue;
    uint64_t got_seq;
    std::vector<VerdictEntry> entries;
    if (!parse_verdict_batch(rp, &got_seq, &entries))
      return CT_FILTER_UNKNOWN_ERROR;
    if (got_seq != seq) continue;  // stale reply for another call
    uint32_t result = CT_FILTER_OK;
    for (auto &e : entries) {
      if (e.result != CT_FILTER_OK) result = e.result;
      c->dirs[0].inject += e.inject_orig;
      c->dirs[1].inject += e.inject_reply;
      for (auto &op : e.ops) c->pending_ops[reply ? 1 : 0].push_back(op);
    }
    return result;
  }
}

}  // namespace

extern "C" {

uint64_t cilium_tpu_open(const char *socket_path, uint8_t debug) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return 0;
  sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, socket_path, sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return 0;
  }
  auto m = std::make_unique<Module>();
  m->fd = fd;

  std::string payload;
  put<uint8_t>(&payload, debug);
  put<uint16_t>(&payload, 0);  // no params
  std::string reply;
  {
    std::lock_guard<std::mutex> lk(m->io_mutex);
    if (!rpc(m.get(), kMsgOpenModule, payload, kMsgModuleId, &reply) ||
        reply.size() < 8) {
      ::close(fd);
      return 0;
    }
  }
  size_t off = 0;
  m->module_id = get<uint64_t>(reply, &off);
  if (m->module_id == 0) {
    ::close(fd);
    return 0;
  }
  std::lock_guard<std::mutex> lk(g_registry_mutex);
  uint64_t handle = g_next_handle++;
  g_modules[handle] = std::move(m);
  return handle;
}

void cilium_tpu_close_module(uint64_t module) {
  std::lock_guard<std::mutex> lk(g_registry_mutex);
  auto it = g_modules.find(module);
  if (it == g_modules.end()) return;
  ::close(it->second->fd);
  g_modules.erase(it);
}

uint32_t cilium_tpu_policy_update_json(uint64_t module, const char *json,
                                       size_t len) {
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  std::string payload;
  put<uint64_t>(&payload, m->module_id);
  put<uint32_t>(&payload, static_cast<uint32_t>(len));
  payload.append(json, len);
  std::lock_guard<std::mutex> lk(m->io_mutex);
  std::string reply;
  if (!rpc(m, kMsgPolicyUpdate, payload, kMsgAck, &reply) || reply.size() < 4)
    return CT_FILTER_UNKNOWN_ERROR;
  size_t off = 0;
  return get<uint32_t>(reply, &off);
}

uint32_t cilium_tpu_on_new_connection(uint64_t module, const char *proto,
                                      uint64_t conn_id, uint8_t ingress,
                                      uint32_t src_id, uint32_t dst_id,
                                      const char *src_addr,
                                      const char *dst_addr,
                                      const char *policy_name) {
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  std::string payload;
  put<uint64_t>(&payload, m->module_id);
  put<uint64_t>(&payload, conn_id);
  put<uint8_t>(&payload, ingress);
  put<uint32_t>(&payload, src_id);
  put<uint32_t>(&payload, dst_id);
  put_str(&payload, proto);
  put_str(&payload, src_addr);
  put_str(&payload, dst_addr);
  put_str(&payload, policy_name);
  std::lock_guard<std::mutex> lk(m->io_mutex);
  std::string reply;
  if (!rpc(m, kMsgNewConnection, payload, kMsgConnResult, &reply) ||
      reply.size() < 12)
    return CT_FILTER_UNKNOWN_ERROR;
  size_t off = 8;  // skip echoed conn_id
  uint32_t res = get<uint32_t>(reply, &off);
  if (res == CT_FILTER_OK) {
    std::lock_guard<std::mutex> ck(m->conns_mutex);
    m->conns[conn_id] = std::make_unique<Connection>();
  }
  return res;
}

uint32_t cilium_tpu_on_data(uint64_t module, uint64_t conn_id, uint8_t reply,
                            uint8_t end_stream, const uint8_t *data,
                            int64_t len, CiliumTpuFilterOp *ops,
                            int32_t *n_ops, uint8_t *inject_orig,
                            int64_t *inject_orig_len, uint8_t *inject_reply,
                            int64_t *inject_reply_len) {
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  Connection *c = m->find_conn(conn_id);
  if (!c) return CT_FILTER_UNKNOWN_CONNECTION;

  uint32_t result = CT_FILTER_OK;
  if (len > 0 || end_stream)
    result = on_data_rpc(m, c, conn_id, reply, end_stream, data, len);

  int d = reply ? 1 : 0;
  int32_t cap = *n_ops, produced = 0;
  while (produced < cap && !c->pending_ops[d].empty()) {
    ops[produced++] = c->pending_ops[d].front();
    c->pending_ops[d].pop_front();
  }
  *n_ops = produced;

  // Hand the inject slices to the caller-owned buffers (the
  // origBuf/replyBuf analog of OnNewConnection, libcilium.h).
  auto drain = [](std::string &src, uint8_t *dst, int64_t *cap_len) {
    int64_t n = std::min<int64_t>(*cap_len, src.size());
    if (dst && n > 0) memcpy(dst, src.data(), n);
    src.erase(0, n);
    *cap_len = n;
  };
  if (inject_orig_len) drain(c->dirs[0].inject, inject_orig, inject_orig_len);
  if (inject_reply_len)
    drain(c->dirs[1].inject, inject_reply, inject_reply_len);
  return result;
}

uint32_t cilium_tpu_on_io(uint64_t module, uint64_t conn_id, uint8_t reply,
                          uint8_t end_stream, const uint8_t *input,
                          int64_t in_len, uint8_t *output, int64_t out_cap,
                          int64_t *out_len) {
  *out_len = 0;
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  Connection *c = m->find_conn(conn_id);
  if (!c) return CT_FILTER_UNKNOWN_CONNECTION;
  Direction &dir = c->dirs[reply ? 1 : 0];

  std::string out;
  std::string incoming(reinterpret_cast<const char *>(input),
                       static_cast<size_t>(in_len));

  // Pre-pass / pre-drop from an earlier verdict
  // (reference: cilium_proxylib.cc:130-166).
  size_t pos = 0;
  if (dir.pass_bytes > 0) {
    size_t take = std::min<size_t>(dir.pass_bytes, incoming.size());
    out.append(incoming, 0, take);
    dir.pass_bytes -= take;
    pos = take;
  } else if (dir.drop_bytes > 0) {
    size_t take = std::min<size_t>(dir.drop_bytes, incoming.size());
    dir.drop_bytes -= take;
    pos = take;
  }
  dir.buffer.append(incoming, pos, std::string::npos);

  // Reverse-injected frames first (reference: cilium_proxylib.cc:186-192).
  if (!dir.inject.empty()) {
    out += dir.inject;
    dir.inject.clear();
  }

  uint32_t result = on_data_rpc(m, c, conn_id, reply, end_stream,
                                reinterpret_cast<const uint8_t *>(
                                    incoming.data()),
                                incoming.size());
  if (result != CT_FILTER_OK) return result;

  int d = reply ? 1 : 0;
  while (!c->pending_ops[d].empty()) {
    CiliumTpuFilterOp op = c->pending_ops[d].front();
    c->pending_ops[d].pop_front();
    int64_t n = op.n_bytes;
    switch (op.op) {
      case CT_FILTEROP_MORE:
        dir.need_bytes = static_cast<int64_t>(dir.buffer.size()) + n;
        break;
      case CT_FILTEROP_PASS: {
        int64_t take = std::min<int64_t>(n, dir.buffer.size());
        out.append(dir.buffer, 0, take);
        dir.buffer.erase(0, take);
        if (n > take) dir.pass_bytes = n - take;
        break;
      }
      case CT_FILTEROP_DROP: {
        int64_t take = std::min<int64_t>(n, dir.buffer.size());
        dir.buffer.erase(0, take);
        if (n > take) dir.drop_bytes = n - take;
        break;
      }
      case CT_FILTEROP_INJECT: {
        if (n > static_cast<int64_t>(dir.inject.size()))
          return CT_FILTER_PARSER_ERROR;
        out.append(dir.inject, 0, n);
        dir.inject.erase(0, n);
        break;
      }
      default:
        return CT_FILTER_PARSER_ERROR;
    }
  }

  if (static_cast<int64_t>(out.size()) > out_cap)
    return CT_FILTER_UNKNOWN_ERROR;
  if (!out.empty()) memcpy(output, out.data(), out.size());
  *out_len = static_cast<int64_t>(out.size());
  return CT_FILTER_OK;
}

void cilium_tpu_close_connection(uint64_t module, uint64_t conn_id) {
  Module *m = find_module(module);
  if (!m) return;
  {
    std::lock_guard<std::mutex> ck(m->conns_mutex);
    m->conns.erase(conn_id);
  }
  std::string payload;
  put<uint64_t>(&payload, conn_id);
  std::lock_guard<std::mutex> lk(m->io_mutex);
  send_msg(m->fd, kMsgClose, payload);
}

}  // extern "C"
