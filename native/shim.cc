// cilium-tpu datapath shim implementation.
//
// Native client of the verdict-service wire protocol
// (cilium_tpu/sidecar/wire.py).  Mirrors the role of the reference's
// Envoy-side GoFilter (reference: envoy/cilium_proxylib.cc): per-module
// socket, per-connection retained buffers and inject slices, and the
// OnIO byte-accounting loop applying PASS/DROP/INJECT/MORE ops.
//
// Threading: one mutex per module serializes socket round trips; a
// global registry mutex guards the handle tables.  Connections follow
// the reference's assumption of single-threaded access per connection.

#include "cilium_tpu_shim.h"

#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

constexpr uint16_t kMagic = 0xC17A;
constexpr uint16_t kMsgOpenModule = 1;
constexpr uint16_t kMsgModuleId = 2;
constexpr uint16_t kMsgNewConnection = 3;
constexpr uint16_t kMsgConnResult = 4;
constexpr uint16_t kMsgDataBatch = 5;
constexpr uint16_t kMsgVerdictBatch = 6;
constexpr uint16_t kMsgClose = 7;
constexpr uint16_t kMsgPolicyUpdate = 8;
constexpr uint16_t kMsgAck = 9;
// Shared-memory transport negotiation/notification (sidecar/shm.py).
// This shim stays on the socket transport: it never sends kMsgShmAttach,
// so the service never emits kMsgShmCredit to it, and the recv loops'
// skip-unknown-frames discipline (`if (got != kMsg...) continue;`)
// keeps it forward-compatible with shm-speaking peers on the same
// service.  Listed here so the constant space stays in one place.
[[maybe_unused]] constexpr uint16_t kMsgShmAttach = 19;
[[maybe_unused]] constexpr uint16_t kMsgShmAttachReply = 20;
[[maybe_unused]] constexpr uint16_t kMsgShmDoorbell = 21;
[[maybe_unused]] constexpr uint16_t kMsgShmCredit = 22;
[[maybe_unused]] constexpr uint16_t kMsgShmDetach = 23;
// Established-flow verdict cache (sidecar/wire.py).  Same coexistence
// contract as shm: this shim never sends kMsgCacheEnable, so the
// service never emits grant/revoke frames to it — the opt-in is the
// compatibility gate, every frame stays on the byte-accounting path.
[[maybe_unused]] constexpr uint16_t kMsgCacheEnable = 24;
[[maybe_unused]] constexpr uint16_t kMsgCacheGrant = 25;
[[maybe_unused]] constexpr uint16_t kMsgCacheRevoke = 26;

// Fan-in session hello (PR 15): fire-and-forget, no reply — a shim
// that never announces an identity quotas under a synthetic
// per-session name; nothing else about the protocol changes, so this
// shim needs no new handling.
[[maybe_unused]] constexpr uint16_t kMsgSessionHello = 27;

struct Direction {
  std::string buffer;       // retained, not-yet-verdicted input
  int64_t pass_bytes = 0;   // verdicted PASS beyond buffered input
  int64_t drop_bytes = 0;   // verdicted DROP beyond buffered input
  int64_t need_bytes = 0;   // parser's MORE threshold (informational)
  std::string inject;       // per-direction inject slice
};

struct Connection {
  Direction dirs[2];  // [0]=orig/request, [1]=reply
  // Ops produced by the service but not yet handed to the caller
  // (cilium_tpu_on_data continuation when the caller's array is small).
  std::deque<CiliumTpuFilterOp> pending_ops[2];
  // Identity/address metadata captured at OnNewConnection so the
  // access logger can emit complete records (reference:
  // envoy/accesslog.cc Logger fills these from the filter state).
  bool ingress = false;
  uint32_t src_id = 0;
  uint32_t dst_id = 0;
  std::string src_addr, dst_addr, proto, policy_name;
  // After a service reconnect the service-side buffer mirror is empty:
  // the next data round per direction must resend the retained
  // (unverdicted) buffer instead of only the new bytes.
  bool resync[2] = {false, false};
};

struct Module {
  int fd = -1;
  uint64_t module_id = 0;
  uint64_t next_seq = 1;
  std::string socket_path;  // for reconnect
  uint8_t debug = 0;
  std::string policy_json;  // last ACCEPTED policy, replayed on reconnect
  std::atomic<uint64_t> accesslog{0};  // attached accesslog handle
  std::mutex io_mutex;
  // Guards the conns map itself (insert/erase/find from different
  // threads); per-connection state still follows the reference's
  // single-thread-per-connection contract (proxylib/libcilium.h).
  std::mutex conns_mutex;
  std::map<uint64_t, std::unique_ptr<Connection>> conns;

  Connection *find_conn(uint64_t conn_id) {
    std::lock_guard<std::mutex> lk(conns_mutex);
    auto it = conns.find(conn_id);
    return it == conns.end() ? nullptr : it->second.get();
  }
};

std::mutex g_registry_mutex;
std::map<uint64_t, std::unique_ptr<Module>> g_modules;
std::atomic<uint64_t> g_next_handle{1};

Module *find_module(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_registry_mutex);
  auto it = g_modules.find(handle);
  return it == g_modules.end() ? nullptr : it->second.get();
}

// --- low-level wire I/O ---------------------------------------------------

bool send_all(int fd, const void *buf, size_t n) {
  const char *p = static_cast<const char *>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void *buf, size_t n) {
  char *p = static_cast<char *>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_msg(int fd, uint16_t type, const std::string &payload) {
  char hdr[8];
  uint16_t magic = kMagic;
  uint32_t len = static_cast<uint32_t>(payload.size());
  memcpy(hdr, &magic, 2);
  memcpy(hdr + 2, &type, 2);
  memcpy(hdr + 4, &len, 4);
  return send_all(fd, hdr, 8) &&
         (payload.empty() || send_all(fd, payload.data(), payload.size()));
}

bool recv_msg(int fd, uint16_t *type, std::string *payload) {
  char hdr[8];
  if (!recv_all(fd, hdr, 8)) return false;
  uint16_t magic;
  uint32_t len;
  memcpy(&magic, hdr, 2);
  memcpy(type, hdr + 2, 2);
  memcpy(&len, hdr + 4, 4);
  if (magic != kMagic) return false;
  payload->resize(len);
  return len == 0 || recv_all(fd, &(*payload)[0], len);
}

template <typename T>
void put(std::string *out, T v) {
  out->append(reinterpret_cast<const char *>(&v), sizeof(T));
}

void put_str(std::string *out, const char *s) {
  uint16_t n = s ? static_cast<uint16_t>(strlen(s)) : 0;
  put<uint16_t>(out, n);
  if (n) out->append(s, n);
}

template <typename T>
T get(const std::string &buf, size_t *off) {
  T v;
  memcpy(&v, buf.data() + *off, sizeof(T));
  *off += sizeof(T);
  return v;
}

// One parsed verdict entry.
struct VerdictEntry {
  uint64_t conn_id;
  uint32_t result;
  std::vector<CiliumTpuFilterOp> ops;
  std::string inject_orig;
  std::string inject_reply;
};

// Bounds-checked parse: the wire peer is a trust boundary — a
// truncated or corrupt payload must fail the message, never read out
// of bounds.
bool parse_verdict_batch(const std::string &p, uint64_t *seq,
                         std::vector<VerdictEntry> *entries) {
  size_t off = 0;
  auto need = [&](size_t k) { return p.size() - off >= k; };
  if (!need(12)) return false;
  *seq = get<uint64_t>(p, &off);
  uint32_t n = get<uint32_t>(p, &off);
  if (n > (1u << 20)) return false;  // implausible entry count
  if (!need(static_cast<size_t>(n) * (8 + 4 * 4))) return false;
  std::vector<uint64_t> conn_ids(n);
  std::vector<uint32_t> results(n), op_counts(n), inj_o(n), inj_r(n);
  for (uint32_t i = 0; i < n; i++) conn_ids[i] = get<uint64_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) results[i] = get<uint32_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) op_counts[i] = get<uint32_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) inj_o[i] = get<uint32_t>(p, &off);
  for (uint32_t i = 0; i < n; i++) inj_r[i] = get<uint32_t>(p, &off);
  entries->resize(n);
  for (uint32_t i = 0; i < n; i++) {
    VerdictEntry &e = (*entries)[i];
    e.conn_id = conn_ids[i];
    e.result = results[i];
    if (op_counts[i] > (1u << 16) ||
        !need(static_cast<size_t>(op_counts[i]) * 16))
      return false;
    e.ops.resize(op_counts[i]);
    for (uint32_t k = 0; k < op_counts[i]; k++) {
      e.ops[k].op = get<uint64_t>(p, &off);
      e.ops[k].n_bytes = get<int64_t>(p, &off);
    }
  }
  for (uint32_t i = 0; i < n; i++) {
    VerdictEntry &e = (*entries)[i];
    if (!need(static_cast<size_t>(inj_o[i]) + inj_r[i])) return false;
    e.inject_orig.assign(p.data() + off, inj_o[i]);
    off += inj_o[i];
    e.inject_reply.assign(p.data() + off, inj_r[i]);
    off += inj_r[i];
  }
  return true;
}

// Synchronous round trip expecting a given reply type; caller holds
// the module io_mutex.
bool rpc(Module *m, uint16_t type, const std::string &payload,
         uint16_t want_type, std::string *reply) {
  if (m->fd < 0 || !send_msg(m->fd, type, payload)) return false;
  uint16_t got;
  for (;;) {
    if (!recv_msg(m->fd, &got, reply)) return false;
    if (got == want_type) return true;
    // Unexpected interleaved message (shouldn't happen with serialized
    // round trips); skip it.
  }
}

// Dial the service socket and run the OpenModule handshake.  Caller
// holds io_mutex.  On success m->fd/m->module_id are fresh.
bool dial_module(Module *m) {
  if (m->fd >= 0) {
    ::close(m->fd);
    m->fd = -1;
  }
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return false;
  sockaddr_un addr;
  memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, m->socket_path.c_str(), sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return false;
  }
  m->fd = fd;
  std::string payload;
  put<uint8_t>(&payload, m->debug);
  put<uint16_t>(&payload, 0);  // no params
  std::string reply;
  if (!rpc(m, kMsgOpenModule, payload, kMsgModuleId, &reply) ||
      reply.size() < 8) {
    ::close(m->fd);
    m->fd = -1;
    return false;
  }
  size_t off = 0;
  m->module_id = get<uint64_t>(reply, &off);
  if (m->module_id == 0) {
    ::close(m->fd);
    m->fd = -1;
    return false;
  }
  return true;
}

bool register_conn_rpc(Module *m, uint64_t conn_id, const Connection *c) {
  std::string payload;
  put<uint64_t>(&payload, m->module_id);
  put<uint64_t>(&payload, conn_id);
  put<uint8_t>(&payload, c->ingress ? 1 : 0);
  put<uint32_t>(&payload, c->src_id);
  put<uint32_t>(&payload, c->dst_id);
  put_str(&payload, c->proto.c_str());
  put_str(&payload, c->src_addr.c_str());
  put_str(&payload, c->dst_addr.c_str());
  put_str(&payload, c->policy_name.c_str());
  std::string reply;
  if (!rpc(m, kMsgNewConnection, payload, kMsgConnResult, &reply) ||
      reply.size() < 12)
    return false;
  size_t off = 8;
  return get<uint32_t>(reply, &off) == CT_FILTER_OK;
}

// Service-restart recovery (the NPDS-reconnect analog, reference:
// proxylib/npds/client.go:133 reconnect loop): dial a fresh module,
// replay the last accepted policy, re-register every live connection,
// and mark all directions for buffer resync — the shim's retained
// buffers are exactly the unverdicted bytes the new service needs.
// Caller holds io_mutex.
bool reconnect_module(Module *m) {
  // Any replay failure closes the fresh fd again: a half-replayed
  // module must look DEAD so the next call re-enters recovery, not
  // half-recovered with unregistered connections.
  auto fail = [m]() {
    if (m->fd >= 0) {
      ::close(m->fd);
      m->fd = -1;
    }
    return false;
  };
  if (!dial_module(m)) return false;
  if (!m->policy_json.empty()) {
    std::string payload;
    put<uint64_t>(&payload, m->module_id);
    put<uint32_t>(&payload, static_cast<uint32_t>(m->policy_json.size()));
    payload += m->policy_json;
    std::string reply;
    if (!rpc(m, kMsgPolicyUpdate, payload, kMsgAck, &reply)) return fail();
    size_t off = 0;
    if (reply.size() < 4 || get<uint32_t>(reply, &off) != CT_FILTER_OK)
      return fail();
  }
  std::lock_guard<std::mutex> ck(m->conns_mutex);
  for (auto &kv : m->conns) {
    if (!register_conn_rpc(m, kv.first, kv.second.get())) return fail();
    kv.second->resync[0] = true;
    kv.second->resync[1] = true;
  }
  return true;
}

// Ship new bytes for a connection/direction; parse verdict entries and
// append their ops/injects to the connection's pending queues.
uint32_t on_data_rpc(Module *m, Connection *c, uint64_t conn_id, bool reply,
                     bool end_stream, const uint8_t *data, int64_t len) {
  std::lock_guard<std::mutex> lk(m->io_mutex);
  int d = reply ? 1 : 0;

  auto build = [&](const char *bytes, int64_t n) {
    uint64_t seq = m->next_seq++;
    std::string payload;
    put<uint64_t>(&payload, seq);
    put<uint32_t>(&payload, 1);
    put<uint64_t>(&payload, conn_id);
    uint8_t flags = (reply ? 1 : 0) | (end_stream ? 2 : 0);
    put<uint8_t>(&payload, flags);
    put<uint32_t>(&payload, static_cast<uint32_t>(n));
    if (n > 0) payload.append(bytes, n);
    return std::make_pair(seq, payload);
  };

  auto attempt = [&](uint64_t seq, const std::string &payload,
                     uint32_t *result) -> bool {
    // false = transport failure (caller may reconnect + retry)
    std::string rp;
    if (m->fd < 0 || !send_msg(m->fd, kMsgDataBatch, payload)) return false;
    for (;;) {
      uint16_t got;
      if (!recv_msg(m->fd, &got, &rp)) return false;
      if (got != kMsgVerdictBatch) continue;
      uint64_t got_seq;
      std::vector<VerdictEntry> entries;
      if (!parse_verdict_batch(rp, &got_seq, &entries)) {
        *result = CT_FILTER_UNKNOWN_ERROR;
        return true;
      }
      if (got_seq != seq) continue;  // stale reply for another call
      *result = CT_FILTER_OK;
      for (auto &e : entries) {
        if (e.result != CT_FILTER_OK) *result = e.result;
        c->dirs[0].inject += e.inject_orig;
        c->dirs[1].inject += e.inject_reply;
        for (auto &op : e.ops) c->pending_ops[d].push_back(op);
      }
      return true;
    }
  };

  // After a reconnect, the service's buffer mirror is empty: ship the
  // whole retained (unverdicted) buffer — which already contains the
  // incoming bytes on the on_io path — instead of only the new bytes.
  uint32_t result = CT_FILTER_UNKNOWN_ERROR;
  bool ok;
  if (c->resync[d] && !c->dirs[d].buffer.empty()) {
    auto [seq, payload] =
        build(c->dirs[d].buffer.data(),
              static_cast<int64_t>(c->dirs[d].buffer.size()));
    ok = attempt(seq, payload, &result);
  } else {
    auto [seq, payload] = build(reinterpret_cast<const char *>(data), len);
    ok = attempt(seq, payload, &result);
  }
  if (ok) {
    c->resync[d] = false;
    return result;
  }

  // Transport failure: reconnect (fresh module + policy + connection
  // replay, all directions marked resync) and retry ONCE.  The on_io
  // path retains the unverdicted bytes in dir.buffer (including this
  // call's); the raw on_data path keeps dir.buffer empty — there the
  // caller owns buffering and passes the full unverdicted data each
  // call (reference OnData contract), so the caller's bytes are the
  // resync payload.
  if (!reconnect_module(m)) return CT_FILTER_UNKNOWN_ERROR;
  const std::string &buf = c->dirs[d].buffer;
  auto [seq, payload] =
      buf.empty() ? build(reinterpret_cast<const char *>(data), len)
                  : build(buf.data(), static_cast<int64_t>(buf.size()));
  if (!attempt(seq, payload, &result)) return CT_FILTER_UNKNOWN_ERROR;
  c->resync[d] = false;
  return result;
}

}  // namespace

namespace {

// --- access log client (reference: envoy/accesslog.cc) --------------------

struct AccessLog {
  std::string path;
  int fd = -1;
  std::mutex mutex;

  bool try_connect() {
    if (fd >= 0) return true;
    fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return false;
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, path.c_str(), sizeof(addr.sun_path) - 1);
    if (connect(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) != 0) {
      ::close(fd);
      fd = -1;
      return false;
    }
    return true;
  }

  // 4-byte big-endian length + JSON body (accesslog/server.py framing);
  // one reconnect attempt per send (reference: accesslog.cc Log's
  // TryConnect-then-retry).
  bool send_frame(const char *json, size_t len) {
    std::lock_guard<std::mutex> lk(mutex);
    for (int attempt = 0; attempt < 2; attempt++) {
      if (!try_connect()) return false;
      uint8_t hdr[4] = {
          static_cast<uint8_t>(len >> 24), static_cast<uint8_t>(len >> 16),
          static_cast<uint8_t>(len >> 8), static_cast<uint8_t>(len)};
      if (send_all(fd, hdr, 4) && send_all(fd, json, len)) return true;
      ::close(fd);
      fd = -1;
    }
    return false;
  }
};

std::mutex g_accesslog_mutex;
// shared_ptr lifetime: an accesslog may be shared across modules and
// threads, and close() must not free it under an in-flight send — the
// erase drops the registry reference while senders holding the shared
// pointer finish safely.
std::map<uint64_t, std::shared_ptr<AccessLog>> g_accesslogs;

std::shared_ptr<AccessLog> find_accesslog(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_accesslog_mutex);
  auto it = g_accesslogs.find(handle);
  return it == g_accesslogs.end() ? nullptr : it->second;
}

void json_escape(std::string *out, const char *s) {
  for (; s && *s; s++) {
    unsigned char c = *s;
    if (c == '"' || c == '\\') {
      out->push_back('\\');
      out->push_back(c);
    } else if (c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", c);
      *out += buf;
    } else {
      out->push_back(c);
    }
  }
}

// Build a LogRecord JSON (accesslog/record.py schema).
std::string verdict_record_json(bool denied, bool ingress, uint32_t src_id,
                                uint32_t dst_id, const char *src_addr,
                                const char *dst_addr, const char *proto,
                                const char *info) {
  std::string j = "{\"type\":\"Request\",\"observation_point\":\"";
  j += ingress ? "Ingress" : "Egress";
  j += "\",\"verdict\":\"";
  j += denied ? "Denied" : "Forwarded";
  j += "\",\"source\":{\"identity\":" + std::to_string(src_id) +
       ",\"ipv4\":\"";
  json_escape(&j, src_addr);
  j += "\"},\"destination\":{\"identity\":" + std::to_string(dst_id) +
       ",\"ipv4\":\"";
  json_escape(&j, dst_addr);
  j += "\"},\"info\":\"";
  json_escape(&j, info);
  j += "\",\"l7\":{\"proto\":\"";
  json_escape(&j, proto);
  j += "\",\"fields\":{}}}";
  return j;
}

// --- proxymap snapshot reader (reference: envoy/proxymap.cc) ---------------

struct ProxyMapRec {
  uint32_t saddr, daddr, sport, dport, proto;
  uint32_t orig_daddr, orig_dport, identity;
};

struct ProxyMapFile {
  std::string path;
  // Snapshot version at last successful load: nanosecond mtime + size
  // (second-granular st_mtime alone would miss rapid re-snapshots).
  uint64_t mtime_ns = 0;
  uint64_t size = 0;
  std::vector<ProxyMapRec> recs;
  std::mutex mutex;

  // Snapshot layout (maps/proxymap.py ProxyMap.save): "CTPM", uint32
  // count, then count * 8 little-endian uint32s per record.  Re-reads
  // only when the file's mtime changed; the header count is validated
  // against the actual file size so a corrupt snapshot returns -1
  // (previous snapshot stays active) instead of over-allocating.
  int64_t load() {
    struct stat st {};
    if (stat(path.c_str(), &st) != 0) return -1;
    uint64_t ver = static_cast<uint64_t>(st.st_mtim.tv_sec) * 1000000000ull +
                   static_cast<uint64_t>(st.st_mtim.tv_nsec);
    {
      std::lock_guard<std::mutex> lk(mutex);
      if (mtime_ns != 0 && ver == mtime_ns &&
          static_cast<uint64_t>(st.st_size) == size)
        return static_cast<int64_t>(recs.size());
    }
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return -1;
    char magic[4];
    uint32_t count = 0;
    std::vector<ProxyMapRec> fresh;
    bool ok = fread(magic, 1, 4, f) == 4 && memcmp(magic, "CTPM", 4) == 0 &&
              fread(&count, 4, 1, f) == 1 &&
              static_cast<uint64_t>(st.st_size) >=
                  8 + static_cast<uint64_t>(count) * sizeof(ProxyMapRec);
    if (ok) {
      fresh.resize(count);
      ok = count == 0 ||
           fread(fresh.data(), sizeof(ProxyMapRec), count, f) == count;
    }
    fclose(f);
    if (!ok) return -1;
    std::lock_guard<std::mutex> lk(mutex);
    recs = std::move(fresh);
    mtime_ns = ver;
    size = static_cast<uint64_t>(st.st_size);
    return static_cast<int64_t>(recs.size());
  }
};

std::mutex g_proxymap_mutex;
std::map<uint64_t, std::shared_ptr<ProxyMapFile>> g_proxymaps;

// --- host map snapshot (reference: envoy/cilium_host_map.cc) ---------------

struct HostMapRec {
  uint32_t addr, plen, identity, tunnel;
};

struct HostMapFile {
  std::string path;
  uint64_t mtime_ns = 0;
  uint64_t size = 0;
  std::vector<HostMapRec> recs;
  std::mutex mutex;

  // Layout (maps/ipcache.py IpcacheMap.save): "CTHM", uint32 count,
  // count * 4 LE uint32s.  Same corruption/versioning rules as
  // ProxyMapFile::load.
  int64_t load() {
    struct stat st {};
    if (stat(path.c_str(), &st) != 0) return -1;
    uint64_t ver = static_cast<uint64_t>(st.st_mtim.tv_sec) * 1000000000ull +
                   static_cast<uint64_t>(st.st_mtim.tv_nsec);
    {
      std::lock_guard<std::mutex> lk(mutex);
      if (mtime_ns != 0 && ver == mtime_ns &&
          static_cast<uint64_t>(st.st_size) == size)
        return static_cast<int64_t>(recs.size());
    }
    FILE *f = fopen(path.c_str(), "rb");
    if (!f) return -1;
    char magic[4];
    uint32_t count = 0;
    std::vector<HostMapRec> fresh;
    bool ok = fread(magic, 1, 4, f) == 4 && memcmp(magic, "CTHM", 4) == 0 &&
              fread(&count, 4, 1, f) == 1 &&
              static_cast<uint64_t>(st.st_size) >=
                  8 + static_cast<uint64_t>(count) * sizeof(HostMapRec);
    if (ok) {
      fresh.resize(count);
      ok = count == 0 ||
           fread(fresh.data(), sizeof(HostMapRec), count, f) == count;
    }
    fclose(f);
    if (!ok) return -1;
    std::lock_guard<std::mutex> lk(mutex);
    recs = std::move(fresh);
    mtime_ns = ver;
    size = static_cast<uint64_t>(st.st_size);
    return static_cast<int64_t>(recs.size());
  }
};

std::mutex g_hostmap_mutex;
std::map<uint64_t, std::shared_ptr<HostMapFile>> g_hostmaps;

std::shared_ptr<HostMapFile> find_hostmap(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_hostmap_mutex);
  auto it = g_hostmaps.find(handle);
  return it == g_hostmaps.end() ? nullptr : it->second;
}

std::shared_ptr<ProxyMapFile> find_proxymap(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_proxymap_mutex);
  auto it = g_proxymaps.find(handle);
  return it == g_proxymaps.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

uint64_t cilium_tpu_open(const char *socket_path, uint8_t debug) {
  auto m = std::make_unique<Module>();
  m->socket_path = socket_path ? socket_path : "";
  m->debug = debug;
  {
    std::lock_guard<std::mutex> lk(m->io_mutex);
    if (!dial_module(m.get())) return 0;
  }
  std::lock_guard<std::mutex> lk(g_registry_mutex);
  uint64_t handle = g_next_handle++;
  g_modules[handle] = std::move(m);
  return handle;
}

void cilium_tpu_close_module(uint64_t module) {
  std::lock_guard<std::mutex> lk(g_registry_mutex);
  auto it = g_modules.find(module);
  if (it == g_modules.end()) return;
  ::close(it->second->fd);
  g_modules.erase(it);
}

uint32_t cilium_tpu_policy_update_json(uint64_t module, const char *json,
                                       size_t len) {
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  std::string payload;
  put<uint64_t>(&payload, m->module_id);
  put<uint32_t>(&payload, static_cast<uint32_t>(len));
  payload.append(json, len);
  std::lock_guard<std::mutex> lk(m->io_mutex);
  std::string reply;
  if (!rpc(m, kMsgPolicyUpdate, payload, kMsgAck, &reply) || reply.size() < 4)
    return CT_FILTER_UNKNOWN_ERROR;
  size_t off = 0;
  uint32_t res = get<uint32_t>(reply, &off);
  if (res == CT_FILTER_OK)
    m->policy_json.assign(json, len);  // replayed on reconnect
  return res;
}

uint32_t cilium_tpu_on_new_connection(uint64_t module, const char *proto,
                                      uint64_t conn_id, uint8_t ingress,
                                      uint32_t src_id, uint32_t dst_id,
                                      const char *src_addr,
                                      const char *dst_addr,
                                      const char *policy_name) {
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  std::string payload;
  put<uint64_t>(&payload, m->module_id);
  put<uint64_t>(&payload, conn_id);
  put<uint8_t>(&payload, ingress);
  put<uint32_t>(&payload, src_id);
  put<uint32_t>(&payload, dst_id);
  put_str(&payload, proto);
  put_str(&payload, src_addr);
  put_str(&payload, dst_addr);
  put_str(&payload, policy_name);
  std::lock_guard<std::mutex> lk(m->io_mutex);
  std::string reply;
  if (!rpc(m, kMsgNewConnection, payload, kMsgConnResult, &reply) ||
      reply.size() < 12)
    return CT_FILTER_UNKNOWN_ERROR;
  size_t off = 8;  // skip echoed conn_id
  uint32_t res = get<uint32_t>(reply, &off);
  if (res == CT_FILTER_OK) {
    auto conn = std::make_unique<Connection>();
    conn->ingress = ingress != 0;
    conn->src_id = src_id;
    conn->dst_id = dst_id;
    conn->src_addr = src_addr ? src_addr : "";
    conn->dst_addr = dst_addr ? dst_addr : "";
    conn->proto = proto ? proto : "";
    conn->policy_name = policy_name ? policy_name : "";
    std::lock_guard<std::mutex> ck(m->conns_mutex);
    m->conns[conn_id] = std::move(conn);
  }
  return res;
}

uint32_t cilium_tpu_on_data(uint64_t module, uint64_t conn_id, uint8_t reply,
                            uint8_t end_stream, const uint8_t *data,
                            int64_t len, CiliumTpuFilterOp *ops,
                            int32_t *n_ops, uint8_t *inject_orig,
                            int64_t *inject_orig_len, uint8_t *inject_reply,
                            int64_t *inject_reply_len) {
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  Connection *c = m->find_conn(conn_id);
  if (!c) return CT_FILTER_UNKNOWN_CONNECTION;

  uint32_t result = CT_FILTER_OK;
  if (len > 0 || end_stream)
    result = on_data_rpc(m, c, conn_id, reply, end_stream, data, len);

  int d = reply ? 1 : 0;
  int32_t cap = *n_ops, produced = 0;
  while (produced < cap && !c->pending_ops[d].empty()) {
    ops[produced++] = c->pending_ops[d].front();
    c->pending_ops[d].pop_front();
  }
  *n_ops = produced;

  // Hand the inject slices to the caller-owned buffers (the
  // origBuf/replyBuf analog of OnNewConnection, libcilium.h).
  auto drain = [](std::string &src, uint8_t *dst, int64_t *cap_len) {
    int64_t n = std::min<int64_t>(*cap_len, src.size());
    if (dst && n > 0) memcpy(dst, src.data(), n);
    src.erase(0, n);
    *cap_len = n;
  };
  if (inject_orig_len) drain(c->dirs[0].inject, inject_orig, inject_orig_len);
  if (inject_reply_len)
    drain(c->dirs[1].inject, inject_reply, inject_reply_len);
  return result;
}

uint32_t cilium_tpu_on_io(uint64_t module, uint64_t conn_id, uint8_t reply,
                          uint8_t end_stream, const uint8_t *input,
                          int64_t in_len, uint8_t *output, int64_t out_cap,
                          int64_t *out_len) {
  *out_len = 0;
  Module *m = find_module(module);
  if (!m) return CT_FILTER_INVALID_INSTANCE;
  Connection *c = m->find_conn(conn_id);
  if (!c) return CT_FILTER_UNKNOWN_CONNECTION;
  Direction &dir = c->dirs[reply ? 1 : 0];

  std::string out;
  std::string incoming(reinterpret_cast<const char *>(input),
                       static_cast<size_t>(in_len));

  // Pre-pass / pre-drop from an earlier verdict
  // (reference: cilium_proxylib.cc:130-166).
  size_t pos = 0;
  if (dir.pass_bytes > 0) {
    size_t take = std::min<size_t>(dir.pass_bytes, incoming.size());
    out.append(incoming, 0, take);
    dir.pass_bytes -= take;
    pos = take;
  } else if (dir.drop_bytes > 0) {
    size_t take = std::min<size_t>(dir.drop_bytes, incoming.size());
    dir.drop_bytes -= take;
    pos = take;
  }
  dir.buffer.append(incoming, pos, std::string::npos);

  // Reverse-injected frames first (reference: cilium_proxylib.cc:186-192).
  if (!dir.inject.empty()) {
    out += dir.inject;
    dir.inject.clear();
  }

  uint32_t result = on_data_rpc(m, c, conn_id, reply, end_stream,
                                reinterpret_cast<const uint8_t *>(
                                    incoming.data()),
                                incoming.size());
  if (result != CT_FILTER_OK) return result;

  int d = reply ? 1 : 0;
  int64_t passed_frames = 0, dropped_frames = 0;
  while (!c->pending_ops[d].empty()) {
    CiliumTpuFilterOp op = c->pending_ops[d].front();
    c->pending_ops[d].pop_front();
    int64_t n = op.n_bytes;
    switch (op.op) {
      case CT_FILTEROP_MORE:
        dir.need_bytes = static_cast<int64_t>(dir.buffer.size()) + n;
        break;
      case CT_FILTEROP_PASS: {
        int64_t take = std::min<int64_t>(n, dir.buffer.size());
        out.append(dir.buffer, 0, take);
        dir.buffer.erase(0, take);
        if (n > take) dir.pass_bytes = n - take;
        passed_frames++;
        break;
      }
      case CT_FILTEROP_DROP: {
        int64_t take = std::min<int64_t>(n, dir.buffer.size());
        dir.buffer.erase(0, take);
        if (n > take) dir.drop_bytes = n - take;
        dropped_frames++;
        break;
      }
      case CT_FILTEROP_INJECT: {
        if (n > static_cast<int64_t>(dir.inject.size()))
          return CT_FILTER_PARSER_ERROR;
        out.append(dir.inject, 0, n);
        dir.inject.erase(0, n);
        break;
      }
      default:
        return CT_FILTER_PARSER_ERROR;
    }
  }

  // Per-request access logging (reference: envoy/accesslog.cc — the
  // C++ side logs each verdict with the connection's identities).
  uint64_t al_handle = m->accesslog.load();
  if (al_handle != 0 && (passed_frames || dropped_frames)) {
    auto al = find_accesslog(al_handle);
    if (al) {
      if (passed_frames) {
        std::string j = verdict_record_json(
            false, c->ingress, c->src_id, c->dst_id, c->src_addr.c_str(),
            c->dst_addr.c_str(), c->proto.c_str(), "");
        for (int64_t i = 0; i < passed_frames; i++)
          al->send_frame(j.data(), j.size());
      }
      if (dropped_frames) {
        std::string j = verdict_record_json(
            true, c->ingress, c->src_id, c->dst_id, c->src_addr.c_str(),
            c->dst_addr.c_str(), c->proto.c_str(), "");
        for (int64_t i = 0; i < dropped_frames; i++)
          al->send_frame(j.data(), j.size());
      }
    }
  }

  if (static_cast<int64_t>(out.size()) > out_cap)
    return CT_FILTER_UNKNOWN_ERROR;
  if (!out.empty()) memcpy(output, out.data(), out.size());
  *out_len = static_cast<int64_t>(out.size());
  return CT_FILTER_OK;
}

void cilium_tpu_close_connection(uint64_t module, uint64_t conn_id) {
  Module *m = find_module(module);
  if (!m) return;
  {
    std::lock_guard<std::mutex> ck(m->conns_mutex);
    m->conns.erase(conn_id);
  }
  std::string payload;
  put<uint64_t>(&payload, conn_id);
  std::lock_guard<std::mutex> lk(m->io_mutex);
  send_msg(m->fd, kMsgClose, payload);
}

// --- access log client ABI -------------------------------------------------

uint64_t cilium_tpu_accesslog_open(const char *socket_path) {
  if (!socket_path || !*socket_path) return 0;
  auto al = std::make_shared<AccessLog>();
  al->path = socket_path;
  std::lock_guard<std::mutex> lk(g_accesslog_mutex);
  uint64_t handle = g_next_handle++;
  g_accesslogs[handle] = std::move(al);
  return handle;
}

void cilium_tpu_accesslog_close(uint64_t handle) {
  std::shared_ptr<AccessLog> al;
  {
    std::lock_guard<std::mutex> lk(g_accesslog_mutex);
    auto it = g_accesslogs.find(handle);
    if (it == g_accesslogs.end()) return;
    al = std::move(it->second);
    g_accesslogs.erase(it);
  }
  // Close the fd under the send mutex so an in-flight send finishes
  // first; stragglers then reconnect-fail harmlessly.
  std::lock_guard<std::mutex> slk(al->mutex);
  if (al->fd >= 0) {
    ::close(al->fd);
    al->fd = -1;
  }
}

uint32_t cilium_tpu_accesslog_send_json(uint64_t handle, const char *json,
                                        size_t len) {
  auto al = find_accesslog(handle);
  if (!al || !json) return 0;
  return al->send_frame(json, len) ? 1 : 0;
}

uint32_t cilium_tpu_accesslog_log_verdict(
    uint64_t handle, uint8_t denied, uint8_t ingress, uint32_t src_id,
    uint32_t dst_id, const char *src_addr, const char *dst_addr,
    const char *proto, const char *info) {
  auto al = find_accesslog(handle);
  if (!al) return 0;
  std::string j = verdict_record_json(denied != 0, ingress != 0, src_id,
                                      dst_id, src_addr ? src_addr : "",
                                      dst_addr ? dst_addr : "",
                                      proto ? proto : "",
                                      info ? info : "");
  return al->send_frame(j.data(), j.size()) ? 1 : 0;
}

void cilium_tpu_set_accesslog(uint64_t module, uint64_t accesslog) {
  Module *m = find_module(module);
  if (m) m->accesslog.store(accesslog);
}

// --- proxymap reader ABI ---------------------------------------------------

uint64_t cilium_tpu_proxymap_open(const char *path) {
  if (!path || !*path) return 0;
  auto pm = std::make_shared<ProxyMapFile>();
  pm->path = path;
  if (pm->load() < 0) return 0;
  std::lock_guard<std::mutex> lk(g_proxymap_mutex);
  uint64_t handle = g_next_handle++;
  g_proxymaps[handle] = std::move(pm);
  return handle;
}

int64_t cilium_tpu_proxymap_refresh(uint64_t handle) {
  auto pm = find_proxymap(handle);
  if (!pm) return -1;
  return pm->load();
}

uint32_t cilium_tpu_proxymap_lookup(uint64_t handle, uint32_t saddr,
                                    uint32_t daddr, uint16_t sport,
                                    uint16_t dport, uint8_t proto,
                                    uint32_t *orig_daddr,
                                    uint32_t *orig_dport,
                                    uint32_t *identity) {
  auto pm = find_proxymap(handle);
  if (!pm) return 0;
  std::lock_guard<std::mutex> lk(pm->mutex);
  for (const auto &r : pm->recs) {
    if (r.saddr == saddr && r.daddr == daddr && r.sport == sport &&
        r.dport == dport && r.proto == proto) {
      if (orig_daddr) *orig_daddr = r.orig_daddr;
      if (orig_dport) *orig_dport = r.orig_dport;
      if (identity) *identity = r.identity;
      return 1;
    }
  }
  return 0;
}

void cilium_tpu_proxymap_close(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_proxymap_mutex);
  g_proxymaps.erase(handle);
}

// --- host map ABI ----------------------------------------------------------

uint64_t cilium_tpu_hostmap_open(const char *path) {
  if (!path || !*path) return 0;
  auto hm = std::make_shared<HostMapFile>();
  hm->path = path;
  if (hm->load() < 0) return 0;
  std::lock_guard<std::mutex> lk(g_hostmap_mutex);
  uint64_t handle = g_next_handle++;
  g_hostmaps[handle] = std::move(hm);
  return handle;
}

int64_t cilium_tpu_hostmap_refresh(uint64_t handle) {
  auto hm = find_hostmap(handle);
  if (!hm) return -1;
  return hm->load();
}

uint32_t cilium_tpu_hostmap_lookup(uint64_t handle, uint32_t addr,
                                   uint32_t *identity,
                                   uint32_t *tunnel_endpoint) {
  auto hm = find_hostmap(handle);
  if (!hm) return 0;
  std::lock_guard<std::mutex> lk(hm->mutex);
  const HostMapRec *best = nullptr;
  for (const auto &r : hm->recs) {
    uint32_t mask =
        r.plen == 0 ? 0u : ~((r.plen >= 32) ? 0u : ((1u << (32 - r.plen)) - 1u));
    if ((addr & mask) == r.addr && (!best || r.plen > best->plen)) best = &r;
  }
  if (!best) return 0;
  if (identity) *identity = best->identity;
  if (tunnel_endpoint) *tunnel_endpoint = best->tunnel;
  return best->plen + 1;
}

void cilium_tpu_hostmap_close(uint64_t handle) {
  std::lock_guard<std::mutex> lk(g_hostmap_mutex);
  g_hostmaps.erase(handle);
}

// --- accept-path composition -----------------------------------------------

uint32_t cilium_tpu_accept(uint64_t module, uint64_t proxymap,
                           uint64_t hostmap, const char *l7_proto,
                           uint64_t conn_id, uint8_t ingress,
                           uint32_t saddr, uint32_t daddr, uint16_t sport,
                           uint16_t dport, uint8_t proto_num,
                           const char *policy_name, uint32_t *orig_daddr,
                           uint32_t *orig_dport, uint32_t *src_id,
                           uint32_t *dst_id) {
  // 1. Original destination + source identity from the proxymap
  // (cilium_bpf_metadata.cc getOriginalDst).
  uint32_t od = daddr, op = dport, sid = 0;
  uint32_t pm_od = 0, pm_op = 0, pm_id = 0;
  bool redirected =
      proxymap != 0 &&
      cilium_tpu_proxymap_lookup(proxymap, saddr, daddr, sport, dport,
                                 proto_num, &pm_od, &pm_op, &pm_id) == 1;
  if (redirected) {
    od = pm_od;
    op = pm_op;
    sid = pm_id;
  }
  // 2. Identity fallbacks via the host map (cilium_host_map.cc
  // resolve); unknown addresses are the reserved world identity.
  constexpr uint32_t kWorldId = 2;
  uint32_t tmp_tun = 0;
  if (sid == 0 && hostmap != 0)
    if (cilium_tpu_hostmap_lookup(hostmap, saddr, &sid, &tmp_tun) == 0)
      sid = 0;
  if (sid == 0) sid = kWorldId;
  uint32_t did = 0;
  if (hostmap != 0)
    if (cilium_tpu_hostmap_lookup(hostmap, od, &did, &tmp_tun) == 0)
      did = 0;
  if (did == 0) did = kWorldId;

  // 3. Register with the verdict service using the ORIGINAL
  // destination (cilium_network_filter.cc onNewConnection).
  char src_str[32], dst_str[32];
  snprintf(src_str, sizeof(src_str), "%u.%u.%u.%u:%u", saddr >> 24,
           (saddr >> 16) & 255, (saddr >> 8) & 255, saddr & 255, sport);
  snprintf(dst_str, sizeof(dst_str), "%u.%u.%u.%u:%u", od >> 24,
           (od >> 16) & 255, (od >> 8) & 255, od & 255, op);
  uint32_t res = cilium_tpu_on_new_connection(
      module, l7_proto, conn_id, ingress, sid, did, src_str, dst_str,
      policy_name);
  if (res == CT_FILTER_OK) {
    if (orig_daddr) *orig_daddr = od;
    if (orig_dport) *orig_dport = op;
    if (src_id) *src_id = sid;
    if (dst_id) *dst_id = did;
  }
  return res;
}

}  // extern "C"
