"""r2d2 batch verdict model — the minimum end-to-end TPU slice.

Replaces the reference's per-request parse+match
(reference: proxylib/r2d2/r2d2parser.go:151-214 + proxylib/proxylib/
policymap.go rule walk) with one device pass over a [flows, bytes] batch:

  1. frame:    first CRLF per flow               (ops.bytescan)
  2. tokenize: cmd = bytes before first space; file = bytes after it when
               the message has exactly one space (msg.split(" ") semantics)
  3. match:    cmd exact-compare + file regex NFA + remote-ID set, reduced
               across the flattened (rule, matcher) rows

Build is a pure function ``PolicyInstance -> device arrays``; evaluation is
jitted and shards on the flow axis.  Bit-identical to the streaming oracle
(tests/test_r2d2_model.py fuzzes both against each other).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bytescan import count_byte, first_occurrence, first_subsequence2, spans_equal_prefix
from ..ops.rxsearch import (
    DeviceDfa,
    DeviceNfa,
    automaton_search_spans,
    compile_automaton,
)
from ..proxylib.parsers.r2d2 import R2d2Rule
from ..proxylib.policy import CompiledPortRules, PolicyInstance
from .base import ConstVerdict, VerdictModel, first_match, pack_remote_sets, remote_ok

MAX_CMD = 8  # longest r2d2 command is "RESET" (5)


@jax.tree_util.register_pytree_node_class
@dataclass
class R2d2BatchModel(VerdictModel):
    nfa: "DeviceDfa | DeviceNfa"  # file-regex automaton, one pattern per row
    cmd_needle: jax.Array  # [R, MAX_CMD] uint8
    cmd_len: jax.Array  # [R] int32
    cmd_any: jax.Array  # [R] bool
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool
    # Per-row compiled match kind (literal|regex|nfa) — attribution
    # labeling only, never device data.  Deliberately EXCLUDED from the
    # pytree aux: aux keys the jit trace cache, and kinds churn (a
    # policy update relabeling same-shaped tables) must hit the
    # existing executable — the traced computation never reads kinds,
    # and nothing host-side consumes a round-tripped pytree's labels.
    match_kinds: tuple = ()
    # Per-row (remote_set_or_None, byte_free) reduction for the verdict
    # cache's byte-invariance analysis (policy/invariance.py) — host
    # aux like match_kinds: outside the pytree, never device data.
    invariant_rows: tuple = ()

    def tree_flatten(self):
        return (
            (self.nfa, self.cmd_needle, self.cmd_len, self.cmd_any,
             self.remote_ids, self.any_remote),
            (),
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    def __call__(self, data, lengths, remotes):
        return r2d2_verdicts(self, data, lengths, remotes)

    def verdicts_attr(self, data, lengths, remotes):
        return r2d2_verdicts_attr(self, data, lengths, remotes)

    def dispatch_bare(self) -> "R2d2BatchModel":
        """Capability marker for the service's shape-keyed dispatch
        cache: models exposing this are passed as jit ARGUMENTS, so two
        models compiled from DIFFERENT policies but the same bucketed
        table shapes share one pytree structure and hit the same
        compiled executable — policy churn re-uploads arrays instead of
        retracing.  (match_kinds is already outside the pytree aux, so
        the model itself is its own bare form.)"""
        return self


def _collect_rows(rules: CompiledPortRules):
    """Flatten (rule, matcher) pairs into device rows.  A rule with no L7
    matchers contributes one always-match row (remote check only)."""
    rows = []  # (remote_set, cmd_exact, file_pattern)
    for rule in rules.rules:
        matchers = rule.l7_matchers or [None]
        for m in matchers:
            if m is None:
                rows.append((rule.allowed_remotes, "", ""))
            else:
                assert isinstance(m, R2d2Rule), f"not an r2d2 rule: {m!r}"
                rows.append((rule.allowed_remotes, m.cmd_exact, m.file_regex))
    return rows


def collect_policy_rows(
    policy: PolicyInstance | None, ingress: bool, port: int
) -> ConstVerdict | list[tuple[frozenset, str, str]]:
    """Resolve the effective (remote_set, cmd, file_regex) rows for
    (policy, direction, port), applying the reference's port cascade:
    exact-port rules OR wildcard-port rules; missing policy or no
    matching port entry -> constant deny (reference: policymap.go:208-236,
    instance.go:157-165).  Exposed so rule-axis sharding can split the
    rows before compiling per-shard tables."""
    if policy is None:
        return ConstVerdict(False)
    side = policy.ingress if ingress else policy.egress
    rows = []
    for key in (port, 0):
        rules = side.by_port.get(key)
        if rules is None:
            continue
        if not rules.have_l7_rules or not rules.rules:
            # Whole set allows any payload from anyone on this port.
            return ConstVerdict(True)
        rows.extend(_collect_rows(rules))
    if not rows:
        return ConstVerdict(False)
    return rows


# Rule-row bucket floor for churned rebuilds (build_r2d2_model pads the
# flattened row count up to the next power of two ≥ this): combined with
# the service's shape-keyed dispatch cache, a policy update that stays
# within the bucket reuses the compiled executable — the recompile cost
# of churn collapses to an array upload.
MIN_RULE_BUCKET = 8


def _rule_bucket(n: int) -> int:
    b = MIN_RULE_BUCKET
    while b < n:
        b *= 2
    return b


def build_r2d2_model(
    policy: PolicyInstance | None, ingress: bool, port: int
) -> ConstVerdict | R2d2BatchModel:
    """Compile the effective rule set for (policy, direction, port) into a
    batch model.  Rule rows are padded to the shape bucket so repeat
    policy churn hits the executable cache (see MIN_RULE_BUCKET)."""
    rows = collect_policy_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    return build_r2d2_model_from_rows(rows, bucket=True)


def build_r2d2_model_from_rows(
    rows: list[tuple[frozenset, str, str]],
    bucket: bool = False,
) -> R2d2BatchModel:
    """Compile (remote_set, cmd, file_regex) rows into device arrays.

    ``bucket=True`` pads the row axis to the next power-of-two bucket
    with rows that can never match (remote set {-1}: identities are
    non-negative, so rem_ok is identically False and a padding row can
    never win the first-match argmax either).  ``match_kinds`` covers
    REAL rows only — an attributed rule id never points at padding."""
    remote_sets = [r[0] for r in rows]
    packed_ids, any_remote = pack_remote_sets(remote_sets)

    n = len(rows)
    n_pad = _rule_bucket(n) if bucket else n
    cmd_needle = np.zeros((n_pad, MAX_CMD), dtype=np.uint8)
    cmd_len = np.zeros((n_pad,), dtype=np.int32)
    cmd_any = np.zeros((n_pad,), dtype=bool)
    for i, (_, cmd, _f) in enumerate(rows):
        b = cmd.encode()
        cmd_needle[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        cmd_len[i] = len(b)
        cmd_any[i] = len(b) == 0
    if n_pad > n:
        ids = np.full((n_pad, packed_ids.shape[1]), -1, dtype=np.int32)
        ids[:n] = packed_ids
        packed_ids = ids
        ar = np.zeros((n_pad,), dtype=bool)
        ar[:n] = any_remote
        any_remote = ar

    nfa = compile_automaton(
        [r[2] for r in rows] + [""] * (n_pad - n)
    )
    kinds = tuple(
        "literal" if not file_rx
        else ("nfa" if isinstance(nfa, DeviceNfa) else "regex")
        for _, _, file_rx in rows
    )
    from ..policy.invariance import reduce_r2d2_rows

    return R2d2BatchModel(
        nfa=nfa,
        cmd_needle=jnp.asarray(cmd_needle),
        cmd_len=jnp.asarray(cmd_len),
        cmd_any=jnp.asarray(cmd_any),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
        match_kinds=kinds,
        invariant_rows=reduce_r2d2_rows(rows),
    )


def _r2d2_rule_hits(
    model: R2d2BatchModel,
    data: jax.Array,  # [F, L] uint8 — buffered stream per flow
    lengths: jax.Array,  # [F] int32
    remotes: jax.Array,  # [F] int32 — source security identity
):
    """Shared frame/tokenize/match pass; returns (complete [F] bool,
    msg_len [F] int32, hits [F, R] bool) — the per-rule-row hit matrix
    both reductions (any-allow and first-match attribution) consume."""
    crlf = first_subsequence2(data, lengths, 0x0D, 0x0A)  # [F]
    complete = crlf < lengths
    msg_len = crlf + 2

    sp = first_occurrence(data, crlf, 0x20)  # first space within msg
    n_spaces = count_byte(data, crlf, 0x20)
    one_space = n_spaces == 1
    file_start = jnp.where(one_space, sp + 1, 0)
    file_end = jnp.where(one_space, crlf, 0)

    cmd_ok = (
        spans_equal_prefix(
            data, jnp.zeros_like(sp), sp, model.cmd_needle, model.cmd_len
        )
        | model.cmd_any[None, :]
    )  # [F, R]
    file_ok = automaton_search_spans(model.nfa, data, file_start, file_end)  # [F, R]
    rem_ok = remote_ok(remotes, model.remote_ids, model.any_remote)  # [F, R]
    return complete, msg_len, cmd_ok & file_ok & rem_ok


@jax.jit
def r2d2_verdicts(
    model: R2d2BatchModel,
    data: jax.Array,  # [F, L] uint8 — buffered stream per flow
    lengths: jax.Array,  # [F] int32
    remotes: jax.Array,  # [F] int32 — source security identity
):
    """Returns (complete [F] bool, msg_len [F] int32, allow [F] bool).

    msg_len counts the CRLF (the oracle's PASS/DROP byte count,
    reference: r2d2parser.go:166).  allow is meaningful only where
    complete.
    """
    complete, msg_len, hits = _r2d2_rule_hits(model, data, lengths, remotes)
    return complete, msg_len, jnp.any(hits, axis=1)


@jax.jit
def r2d2_verdicts_attr(
    model: R2d2BatchModel,
    data: jax.Array,
    lengths: jax.Array,
    remotes: jax.Array,
):
    """r2d2_verdicts plus the deciding rule row: (complete, msg_len,
    allow, rule [F] int32).  ``rule`` is the FIRST matching flattened
    (rule, matcher) row — the host oracle's first-match walk order —
    or -1 where not allowed; computed by an argmax over the same hit
    matrix in the same fused pass."""
    complete, msg_len, hits = _r2d2_rule_hits(model, data, lengths, remotes)
    allow = jnp.any(hits, axis=1)
    return complete, msg_len, allow, first_match(hits, allow)
