"""Shared verdict-model plumbing: remote-ID sets and the port cascade.

The device formula for one compiled port rule set
(reference semantics: proxylib/proxylib/policymap.go:91-171):

    allow[f] = OR_r ( remote_ok[f, r] AND l7_match[f, r] )

with the degenerate cases (no L7 rules anywhere / empty rule list) folding
to a constant at build time.  The port cascade (exact port, then wildcard 0,
reference: policymap.go:208-236) ORs two such results and is resolved when
the model is built for a concrete port.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

MAX_REMOTES = 32


@dataclass
class ConstVerdict:
    """A rule set whose outcome doesn't depend on the payload."""

    allow: bool

    def __call__(self, *_args, **_kwargs):
        return self.allow


class VerdictModel:
    """Base for device-backed batch verdict models."""

    n_rules: int = 0


class SeamProbe(VerdictModel):
    """Diagnostic model for DaemonConfig.seam_probe: a minimal real
    device op (all frames complete, all allowed) that keeps the full
    dispatch -> device call -> readback path alive while removing the
    verdict-compute term — so latbench can measure what the sidecar
    seam itself adds.  Matches the (complete, msg_len, allow) batch
    model contract."""

    match_kinds: tuple = ("probe",)

    def __call__(self, data, lengths, remotes):
        ok = jnp.asarray(lengths) >= 0
        return ok, jnp.asarray(lengths), ok

    def verdicts_attr(self, data, lengths, remotes):
        ok = jnp.asarray(lengths) >= 0
        return ok, jnp.asarray(lengths), ok, jnp.zeros_like(
            jnp.asarray(lengths, jnp.int32)
        )


def first_match(hits: jax.Array, allow: jax.Array) -> jax.Array:
    """[F] int32 index of the FIRST matching rule row per flow, -1
    where nothing allowed — the device half of rule attribution.

    Priority order is row order, which the model builders construct in
    the host oracle's walk order (exact-port rules before wildcard-port
    rules, matchers within a rule in declaration order), so
    ``argmax`` over the boolean hit matrix IS the host's first-match
    semantics.  Rides in the same fused computation as the verdict
    reduction — no extra device round-trip."""
    return jnp.where(
        allow, jnp.argmax(hits, axis=1).astype(jnp.int32), jnp.int32(-1)
    )


def pack_remote_sets(remote_sets: list[frozenset[int]]) -> tuple[np.ndarray, np.ndarray]:
    """Pack per-rule allowed-remote sets into [R, MAX_REMOTES] int32 plus a
    per-rule 'empty set allows any remote' flag (reference:
    policymap.go:92-98)."""
    r = len(remote_sets)
    ids = np.zeros((r, MAX_REMOTES), dtype=np.int32)
    any_remote = np.zeros((r,), dtype=bool)
    for i, s in enumerate(remote_sets):
        if not s:
            any_remote[i] = True
            continue
        if len(s) > MAX_REMOTES:
            raise ValueError(
                f"rule allows {len(s)} remotes (max {MAX_REMOTES}); "
                "shard the rule or raise MAX_REMOTES"
            )
        ids[i, : len(s)] = sorted(s)
        # pad with the first id so padding never matches a real remote 0
        ids[i, len(s):] = ids[i, 0]
    return ids, any_remote


def remote_ok(
    remote_ids: jax.Array,  # [F] int32
    packed_ids: jax.Array,  # [R, MAX_REMOTES] int32
    any_remote: jax.Array,  # [R] bool
) -> jax.Array:
    """[F, R] bool: flow f's remote is allowed by rule r."""
    hit = jnp.any(
        remote_ids[:, None, None] == packed_ids[None, :, :], axis=2
    )  # [F, R]
    return hit | any_remote[None, :]
