"""Memcached batch verdict model — device-side (command/opcode, key) ACL.

Replaces the per-request rule walk of the reference's memcached parsers
(reference: proxylib/memcached/parser.go:47-110 Rule.Matches) with one
device pass over pre-framed requests:

  allow[f] = OR_r ( remote_ok AND cmd_ok AND (no_key OR key_ok) )

- cmd_ok: binary flows index a [R, 256] opcode table; text flows index
  a [R, NCMDS] command table over the global text-command vocabulary
  (MEMCACHE_OPCODE_MAP); empty rules match everything
- key_ok by rule mode: exact (span equality), prefix (span starts-with),
  regex (shared NFA search), or none
- multi-key frames (text multi-get) are judged host-side — the device
  path covers the <= 1 key case, the overwhelming steady state; callers
  fall back on overflow exactly like the Kafka topic path

Framing (header fields, token split, reply sequencing, denial-inject
ordering) stays host-side in the streaming parsers.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bytescan import spans_equal_prefix, spans_start_with
from ..ops.rxsearch import (
    DeviceDfa,
    DeviceNfa,
    automaton_search_spans,
    compile_automaton,
)
from ..proxylib.parsers.memcached import MEMCACHE_OPCODE_MAP, MemcacheRule
from ..proxylib.policy import CompiledPortRules, PolicyInstance
from .base import ConstVerdict, VerdictModel, pack_remote_sets, remote_ok

MAX_KEY = 96

# Global text-command vocabulary (order fixed at import): every text
# command any rule group can allow.  Flows carry an index into this.
TEXT_COMMANDS: tuple[str, ...] = tuple(
    sorted({c for text, _ in MEMCACHE_OPCODE_MAP.values() for c in text})
)
TEXT_COMMAND_INDEX = {c: i for i, c in enumerate(TEXT_COMMANDS)}

KEY_MODE_NONE = 0
KEY_MODE_EXACT = 1
KEY_MODE_PREFIX = 2
KEY_MODE_REGEX = 3


@jax.tree_util.register_pytree_node_class
@dataclass
class MemcacheBatchModel(VerdictModel):
    nfa: "DeviceDfa | DeviceNfa"  # keyRegex rows ('' for non-regex rules)
    op_tab: jax.Array  # [R, 256] bool — allowed binary opcodes
    cmd_tab: jax.Array  # [R, NCMDS] bool — allowed text commands
    empty_rule: jax.Array  # [R] bool — matches anything
    key_mode: jax.Array  # [R] int32
    key_needle: jax.Array  # [R, MAX_KEY] uint8
    key_needle_len: jax.Array  # [R] int32
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool

    def tree_flatten(self):
        return (
            (self.nfa, self.op_tab, self.cmd_tab, self.empty_rule,
             self.key_mode, self.key_needle, self.key_needle_len,
             self.remote_ids, self.any_remote),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    def __call__(self, key_data, key_len, has_key, is_binary, opcode,
                 cmd_id, remotes):
        return memcache_verdicts(
            self, key_data, key_len, has_key, is_binary, opcode, cmd_id,
            remotes,
        )


def _collect_rows(rules: CompiledPortRules):
    rows = []  # (remote_set, MemcacheRule | None)
    for rule in rules.rules:
        matchers = rule.l7_matchers or [None]
        for m in matchers:
            if m is not None and not isinstance(m, MemcacheRule):
                raise AssertionError(f"not a memcache rule: {m!r}")
            rows.append((rule.allowed_remotes, m))
    return rows


def build_memcache_model(
    policy: PolicyInstance | None, ingress: bool, port: int
) -> ConstVerdict | MemcacheBatchModel:
    """Port-cascade build (reference: policymap.go:208-236)."""
    if policy is None:
        return ConstVerdict(False)
    side = policy.ingress if ingress else policy.egress
    rows = []
    for key in (port, 0):
        rules = side.by_port.get(key)
        if rules is None:
            continue
        if not rules.have_l7_rules or not rules.rules:
            return ConstVerdict(True)
        rows.extend(_collect_rows(rules))
    if not rows:
        return ConstVerdict(False)

    packed_ids, any_remote = pack_remote_sets([r[0] for r in rows])
    n = len(rows)
    op_tab = np.zeros((n, 256), bool)
    cmd_tab = np.zeros((n, len(TEXT_COMMANDS)), bool)
    empty_rule = np.zeros((n,), bool)
    key_mode = np.zeros((n,), np.int32)
    key_needle = np.zeros((n, MAX_KEY), np.uint8)
    key_needle_len = np.zeros((n,), np.int32)
    patterns = []
    for i, (_, m) in enumerate(rows):
        if m is None or m.empty:
            empty_rule[i] = True
            patterns.append("")
            continue
        for op in m.bin_opcodes:
            op_tab[i, op] = True
        for c in m.text_cmds:
            cmd_tab[i, TEXT_COMMAND_INDEX[c]] = True
        if m.key_exact:
            key_mode[i] = KEY_MODE_EXACT
            needle = m.key_exact
        elif m.key_prefix:
            key_mode[i] = KEY_MODE_PREFIX
            needle = m.key_prefix
        elif m.key_compiled is not None:
            key_mode[i] = KEY_MODE_REGEX
            needle = b""
        else:
            key_mode[i] = KEY_MODE_NONE
            needle = b""
        if len(needle) > MAX_KEY:
            raise ValueError(
                f"memcache key needle exceeds MAX_KEY ({len(needle)})"
            )
        key_needle[i, : len(needle)] = np.frombuffer(needle, np.uint8)
        key_needle_len[i] = len(needle)
        patterns.append(m.key_regex if key_mode[i] == KEY_MODE_REGEX else "")

    return MemcacheBatchModel(
        nfa=compile_automaton(patterns),
        op_tab=jnp.asarray(op_tab),
        cmd_tab=jnp.asarray(cmd_tab),
        empty_rule=jnp.asarray(empty_rule),
        key_mode=jnp.asarray(key_mode),
        key_needle=jnp.asarray(key_needle),
        key_needle_len=jnp.asarray(key_needle_len),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
    )


def encode_memcache_batch(frames, f_pad: int | None = None):
    """Host-side batch packing: [(is_binary, opcode, command, keys)] ->
    device arrays + overflow flags.  overflow marks frames the device
    path cannot judge (multi-key, oversized key, unknown text command);
    callers fall back to the host oracle for those."""
    n = len(frames)
    f = f_pad or n
    key_data = np.zeros((f, MAX_KEY), np.uint8)
    key_len = np.zeros((f,), np.int32)
    has_key = np.zeros((f,), bool)
    is_binary = np.zeros((f,), bool)
    opcode = np.zeros((f,), np.int32)
    cmd_id = np.zeros((f,), np.int32)
    overflow = np.zeros((n,), bool)
    for i, (binary, op, command, keys) in enumerate(frames):
        if len(keys) > 1:
            overflow[i] = True
            continue
        key = keys[0] if keys else None
        if key is not None and len(key) > MAX_KEY:
            overflow[i] = True
            continue
        is_binary[i] = binary
        if binary:
            opcode[i] = op
        else:
            idx = TEXT_COMMAND_INDEX.get(command)
            if idx is None:
                overflow[i] = True
                continue
            cmd_id[i] = idx
        if key is not None:
            has_key[i] = True
            if key:
                key_data[i, : len(key)] = np.frombuffer(key, np.uint8)
            key_len[i] = len(key)
    return key_data, key_len, has_key, is_binary, opcode, cmd_id, overflow


@jax.jit
def memcache_verdicts(
    model: MemcacheBatchModel,
    key_data: jax.Array,  # [F, MAX_KEY] uint8
    key_len: jax.Array,  # [F] int32
    has_key: jax.Array,  # [F] bool
    is_binary: jax.Array,  # [F] bool
    opcode: jax.Array,  # [F] int32
    cmd_id: jax.Array,  # [F] int32
    remotes: jax.Array,  # [F] int32
) -> jax.Array:
    """allow [F] bool."""
    op_ok = model.op_tab[:, opcode].T  # [F, R]
    cmd_ok_text = model.cmd_tab[:, cmd_id].T  # [F, R]
    cmd_ok = jnp.where(is_binary[:, None], op_ok, cmd_ok_text)

    zeros = jnp.zeros_like(key_len)
    exact = spans_equal_prefix(
        key_data, zeros, key_len, model.key_needle, model.key_needle_len
    )
    prefix = spans_start_with(
        key_data, zeros, key_len, model.key_needle, model.key_needle_len
    )
    regex = automaton_search_spans(model.nfa, key_data, zeros, key_len)
    mode = model.key_mode[None, :]
    key_ok = jnp.where(
        mode == KEY_MODE_EXACT,
        exact,
        jnp.where(
            mode == KEY_MODE_PREFIX,
            prefix,
            jnp.where(mode == KEY_MODE_REGEX, regex, True),
        ),
    )
    key_ok = ~has_key[:, None] | key_ok

    rem = remote_ok(remotes, model.remote_ids, model.any_remote)
    l7_ok = model.empty_rule[None, :] | (cmd_ok & key_ok)
    return jnp.any(rem & l7_ok, axis=1)
