"""DNS name-policy batch verdict model — the first length-prefixed
protocol family on the batched-verdict hot path.

Replaces the reference's per-request dnsproxy name walk (reference:
pkg/fqdn + the proxylib-style per-rule regex loop) with one fused
device pass over a [flows, bytes] batch of DNS-over-TCP query frames
(2-byte length prefix + 12-byte header + QNAME label sequence +
QTYPE/QCLASS):

  1. frame:    msg_len from the length prefix; complete = frame fits
  2. name:     a bounded label walk (MAX_LABELS fori_loop steps) finds
               the QNAME span, validates it (no compression pointers,
               labels <= 63, question section complete), and rewrites
               the row in place to the DOTTED, 0x20-folded name —
               interior length bytes become '.', A-Z fold to a-z
  3. match:    exact-name needle compare + wildcard/regex rows on the
               shared DFA/NFA automaton tier + remote-ID set, reduced
               across the flattened (rule, matcher) rows

Build is a pure function ``PolicyInstance -> device arrays``; rule rows
pad to the power-of-two churn bucket like r2d2; evaluation is jitted
and shards on the flow axis (parallel/rulesharding.mesh_dns_model is
the mesh-resident twin).  Bit-identical to the streaming oracle
(proxylib/parsers/dns.py) — tests/test_dns_model.py fuzzes both; the
structural bounds (MAX_LABELS etc.) are shared constants so the two
rungs cannot drift.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bytescan import spans_equal_prefix
from ..ops.rxsearch import (
    DeviceDfa,
    DeviceNfa,
    automaton_search_spans,
    compile_automaton,
)
from ..proxylib.parsers.dns import (
    DNS_HEADER_LEN,
    DNS_PREFIX_LEN,
    MAX_LABEL,
    MAX_LABELS,
    DnsRule,
)
from ..proxylib.policy import CompiledPortRules, PolicyInstance
from .base import ConstVerdict, VerdictModel, first_match, pack_remote_sets, remote_ok
from .r2d2 import _rule_bucket

# Smallest well-formed query frame: prefix + header + root name + Q.
DNS_MIN_FRAME = DNS_PREFIX_LEN + DNS_HEADER_LEN + 1 + 4
_QNAME_OFF = DNS_PREFIX_LEN + DNS_HEADER_LEN  # first length byte


@jax.tree_util.register_pytree_node_class
@dataclass
class DnsBatchModel(VerdictModel):
    nfa: "DeviceDfa | DeviceNfa"  # pattern/regex automaton, one row each
    name_needle: jax.Array  # [R, W] uint8 — exact names, dotted+folded
    name_len: jax.Array  # [R] int32 (-1 = row matches via automaton/any)
    name_any: jax.Array  # [R] bool — byte-free always-match rows
    use_rx: jax.Array  # [R] bool — row decided by the automaton tier
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool
    # Host-side aux, deliberately OUTSIDE the pytree (see
    # R2d2BatchModel.match_kinds): the trace never reads them, and
    # keeping them out of aux keys churn relabels onto the compiled
    # executable.
    match_kinds: tuple = ()
    invariant_rows: tuple = ()

    def tree_flatten(self):
        return (
            (self.nfa, self.name_needle, self.name_len, self.name_any,
             self.use_rx, self.remote_ids, self.any_remote),
            (),
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    def __call__(self, data, lengths, remotes):
        return dns_verdicts(self, data, lengths, remotes)

    def verdicts_attr(self, data, lengths, remotes):
        return dns_verdicts_attr(self, data, lengths, remotes)

    def dispatch_bare(self) -> "DnsBatchModel":
        """Shape-keyed dispatch-cache marker (see R2d2BatchModel):
        same-bucketed churn rebuilds share one compiled executable."""
        return self


def _collect_rows(rules: CompiledPortRules):
    rows = []  # (remote_set, DnsRule | None)
    for rule in rules.rules:
        matchers = rule.l7_matchers or [None]
        for m in matchers:
            if m is not None:
                assert isinstance(m, DnsRule), f"not a dns rule: {m!r}"
            rows.append((rule.allowed_remotes, m))
    return rows


def collect_dns_policy_rows(
    policy: PolicyInstance | None, ingress: bool, port: int
) -> ConstVerdict | list:
    """Effective (remote_set, DnsRule|None) rows for (policy,
    direction, port) under the reference port cascade — the same
    flattened first-match row order the host ``matches_at`` walks
    (models/r2d2.collect_policy_rows is the template)."""
    if policy is None:
        return ConstVerdict(False)
    side = policy.ingress if ingress else policy.egress
    rows = []
    for key in (port, 0):
        rules = side.by_port.get(key)
        if rules is None:
            continue
        if not rules.have_l7_rules or not rules.rules:
            return ConstVerdict(True)
        rows.extend(_collect_rows(rules))
    if not rows:
        return ConstVerdict(False)
    return rows


def build_dns_model(
    policy: PolicyInstance | None, ingress: bool, port: int
) -> ConstVerdict | DnsBatchModel:
    rows = collect_dns_policy_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    return build_dns_model_from_rows(rows, bucket=True)


def dns_row_arrays(rows: list, n_pad: int, width: int | None = None):
    """Host arrays for (remote_set, DnsRule|None) rows padded to
    ``n_pad`` (padding rows are dead: remote set {-1}, needle_len -1,
    never-accepting automaton slot).  Shared by the single-chip build
    and the rule-axis sharded build so the two cannot drift.  Returns
    (needle, n_len, n_any, use_rx, packed_ids, any_remote, patterns)."""
    exact = [
        (r.name.encode("latin-1", "replace") if r is not None else b"")
        for _, r in rows
    ]
    if width is None:
        # The needle must hold the WHOLE longest exact name (bounded by
        # the MAX_LABELS walk at ~2.5KB): truncating here would make
        # the exact compare a prefix compare — a device over-allow the
        # host oracle never produces.
        width = max((len(b) for b in exact), default=0)
        width = max(8, (width + 7) // 8 * 8)
    needle = np.zeros((n_pad, width), np.uint8)
    n_len = np.full((n_pad,), -1, np.int32)
    n_any = np.zeros((n_pad,), bool)
    use_rx = np.zeros((n_pad,), bool)
    patterns = []
    for i, (_, rule) in enumerate(rows):
        if rule is None or not (rule.name or rule.pattern or rule.regex):
            n_any[i] = True
            patterns.append("")
            continue
        if rule.name:
            b = exact[i]
            assert len(b) <= width, "needle width must cover every name"
            needle[i, : len(b)] = np.frombuffer(b, np.uint8)
            n_len[i] = len(b)
            patterns.append("")
            continue
        use_rx[i] = True
        patterns.append(rule.device_pattern())
    packed_ids, any_remote = pack_remote_sets([r[0] for r in rows])
    n = len(rows)
    if n_pad > n:
        ids = np.full((n_pad, packed_ids.shape[1]), -1, np.int32)
        ids[:n] = packed_ids
        packed_ids = ids
        ar = np.zeros((n_pad,), bool)
        ar[:n] = any_remote
        any_remote = ar
    patterns += [""] * (n_pad - n)
    return needle, n_len, n_any, use_rx, packed_ids, any_remote, patterns


def build_dns_model_from_rows(
    rows: list, bucket: bool = False
) -> DnsBatchModel:
    """Compile (remote_set, DnsRule|None) rows into device arrays;
    ``bucket=True`` pads the row axis to the power-of-two churn bucket
    (models/r2d2.MIN_RULE_BUCKET semantics)."""
    n = len(rows)
    n_pad = _rule_bucket(n) if bucket else n
    (needle, n_len, n_any, use_rx, packed_ids, any_remote,
     patterns) = dns_row_arrays(rows, n_pad)
    nfa = compile_automaton(patterns)
    kinds = tuple(
        "literal" if not (r is not None and (r.pattern or r.regex))
        else ("nfa" if isinstance(nfa, DeviceNfa) else "regex")
        for _, r in rows
    )
    from ..policy.invariance import reduce_dns_rows

    return DnsBatchModel(
        nfa=nfa,
        name_needle=jnp.asarray(needle),
        name_len=jnp.asarray(n_len),
        name_any=jnp.asarray(n_any),
        use_rx=jnp.asarray(use_rx),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
        match_kinds=kinds,
        invariant_rows=reduce_dns_rows(rows),
    )


def _dns_name_span(data: jax.Array, lengths: jax.Array):
    """Frame + QNAME structure of each row's FIRST prefixed frame.

    Returns (complete [F] bool, msg_len [F] i32, valid [F] bool,
    span_start [F] i32, span_end [F] i32, dotted [F, L] u8) where
    ``dotted`` is the row rewritten in place to the dotted 0x20-folded
    name over [span_start, span_end) — interior label-length bytes
    become '.', the leading length byte and terminal zero sit outside
    the span.  The label walk is ONE lax.scan over the byte columns
    (each flow's single label chain advances when the scan reaches its
    current label-length position — O(F·L) total, column slices only,
    no gathers); every structural bound mirrors
    proxylib.parsers.dns.parse_dns_query exactly, so a query invalid
    on one rung is invalid on both."""
    f, l = data.shape
    lengths = jnp.asarray(lengths, jnp.int32)
    if l < DNS_MIN_FRAME:
        z = jnp.zeros((f,), jnp.int32)
        return (
            jnp.zeros((f,), bool), z, jnp.zeros((f,), bool), z, z, data,
        )
    plen = (
        data[:, 0].astype(jnp.int32) << 8
    ) | data[:, 1].astype(jnp.int32)
    msg_len = plen + DNS_PREFIX_LEN
    complete = (lengths >= DNS_PREFIX_LEN) & (msg_len <= lengths)
    qd = (data[:, 6].astype(jnp.int32) << 8) | data[:, 7].astype(jnp.int32)
    limit = jnp.minimum(msg_len, l)  # the walk never leaves the frame
    invalid0 = ~complete | (msg_len < DNS_MIN_FRAME) | (qd < 1)

    def body(carry, col):
        pos, done, invalid, nlab = carry
        c, lb = col
        lb = lb.astype(jnp.int32)
        at = (pos == c) & ~done & ~invalid
        readable = c < limit
        invalid = invalid | (at & ~readable)
        act = at & readable
        terminal = act & (lb == 0)
        done = done | terminal
        step = act & ~terminal
        # Compression pointer / oversized label / too many labels.
        bad = (lb > MAX_LABEL) | (nlab >= MAX_LABELS)
        invalid = invalid | (step & bad)
        step = step & ~bad
        pos = jnp.where(step, pos + 1 + lb, pos)
        nlab = nlab + step.astype(jnp.int32)
        return (pos, done, invalid, nlab), step

    (pos, done, invalid, _), sep_cols = jax.lax.scan(
        body,
        (jnp.full((f,), _QNAME_OFF, jnp.int32),
         jnp.zeros((f,), bool), invalid0, jnp.zeros((f,), jnp.int32)),
        (jnp.arange(l, dtype=jnp.int32), data.T),
    )
    is_sep = sep_cols.T  # [F, L]: True at label-length byte positions
    # Never terminated (chain left the row / too deep) or a question
    # section that cannot hold QTYPE+QCLASS: invalid.
    invalid = invalid | ~done | (pos + 5 > msg_len)
    valid = ~invalid
    span_start = jnp.full((f,), _QNAME_OFF + 1, jnp.int32)
    span_end = jnp.where(valid, pos, span_start)
    upper = (data >= jnp.uint8(0x41)) & (data <= jnp.uint8(0x5A))
    folded = jnp.where(upper, data + jnp.uint8(0x20), data)
    dotted = jnp.where(is_sep, jnp.uint8(0x2E), folded)
    return complete, msg_len, valid, span_start, span_end, dotted


def _dns_rule_hits(
    model: DnsBatchModel,
    data: jax.Array,  # [F, L] uint8 — buffered stream per flow
    lengths: jax.Array,  # [F] int32
    remotes: jax.Array,  # [F] int32 — source security identity
):
    """Shared frame/name/match pass; returns (complete, msg_len,
    hits [F, R] bool) — consumed by both reductions (any-allow and
    first-match attribution), like models/r2d2._r2d2_rule_hits."""
    complete, msg_len, valid, s, e, dotted = _dns_name_span(data, lengths)
    exact_ok = spans_equal_prefix(
        dotted, s, e, model.name_needle, model.name_len
    )  # [F, R]
    rx_ok = automaton_search_spans(model.nfa, dotted, s, e)  # [F, R]
    # The QNAME validity gate masks name-CONSTRAINED rows only: a
    # malformed question can never satisfy a name rule, but a
    # byte-free "allow these peers' DNS" row admits any complete
    # frame — the invariance contract the verdict cache's byte-free
    # claim rests on (policy/invariance.reduce_dns_rows).
    name_ok = model.name_any[None, :] | (
        (exact_ok | (model.use_rx[None, :] & rx_ok)) & valid[:, None]
    )
    rem_ok = remote_ok(remotes, model.remote_ids, model.any_remote)
    return complete, msg_len, name_ok & rem_ok


@jax.jit
def dns_verdicts(
    model: DnsBatchModel,
    data: jax.Array,
    lengths: jax.Array,
    remotes: jax.Array,
):
    """(complete [F] bool, msg_len [F] i32, allow [F] bool) — msg_len
    is the whole prefixed frame; allow meaningful only where
    complete.  A structurally invalid query matches no rule."""
    complete, msg_len, hits = _dns_rule_hits(model, data, lengths, remotes)
    return complete, msg_len, jnp.any(hits, axis=1)


@jax.jit
def dns_verdicts_attr(
    model: DnsBatchModel,
    data: jax.Array,
    lengths: jax.Array,
    remotes: jax.Array,
):
    """dns_verdicts plus the deciding rule row (first-match argmax over
    the same fused hit matrix — the host matches_at walk order)."""
    complete, msg_len, hits = _dns_rule_hits(model, data, lengths, remotes)
    allow = jnp.any(hits, axis=1)
    return complete, msg_len, allow, first_match(hits, allow)
