"""Kafka batch verdict model: topic ACLs as one device pass.

Replaces the reference's per-request rule walk (reference:
pkg/kafka/policy.go:200 MatchesRule over []PortRuleKafka) with a batched
evaluation over [F] parsed request headers and [F, T] topic lists:

  base[f, r]   = api-key-mask ∧ version ∧ clientID/nil-request handling
  simple[f]    = ∃r: (rule topic empty ∨ no topics) ∧ base
  cover[f, t]  = ∃r: rule topic == topic[f, t] ∧ base
  allowed[f]   = simple ∨ (topics present ∧ ∀t cover)

Requests are parsed host-side (cilium_tpu.kafka.request — the wire format
is variable-length and branchy, poor fit for the MXU) into fixed-shape
tensors; all rule matching runs on device.  Bit-identical to the host
oracle (cilium_tpu.kafka.policy.matches_rule), fuzz-checked in tests.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..kafka.request import (
    FIND_COORDINATOR_KEY,
    PARSED_TOPIC_KEYS,
    RequestMessage,
    TOPIC_API_KEYS,
)
from ..policy.api import PortRuleKafka
from .base import ConstVerdict, pack_remote_sets, remote_ok

MAX_API_KEY = 64
MAX_TOPICS = 8  # topics per request tensor; overflowing requests are
# flagged and must be decided by the host oracle (fail closed on device)
MAX_TOPIC_LEN = 256  # Kafka topics are <= 249 chars (api/kafka.go:238)
MAX_CLIENT_LEN = 64


@jax.tree_util.register_pytree_node_class
@dataclass
class KafkaBatchModel:
    api_key_mask: jax.Array  # [R, MAX_API_KEY] bool
    version: jax.Array  # [R] int32
    version_any: jax.Array  # [R] bool
    client: jax.Array  # [R, MAX_CLIENT_LEN] uint8
    client_len: jax.Array  # [R] int32
    client_any: jax.Array  # [R] bool
    topic: jax.Array  # [R, MAX_TOPIC_LEN] uint8
    topic_len: jax.Array  # [R] int32
    topic_any: jax.Array  # [R] bool
    is_topic_key: jax.Array  # [MAX_API_KEY] bool
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool

    def tree_flatten(self):
        return (
            (self.api_key_mask, self.version, self.version_any, self.client,
             self.client_len, self.client_any, self.topic, self.topic_len,
             self.topic_any, self.is_topic_key, self.remote_ids,
             self.any_remote),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    def __call__(self, batch, remotes):
        return kafka_verdicts(self, batch, remotes)


def _pad_bytes(s: str, width: int) -> tuple[np.ndarray, int]:
    b = s.encode()[:width]
    out = np.zeros((width,), np.uint8)
    out[: len(b)] = np.frombuffer(b, np.uint8)
    return out, len(b)


def build_kafka_model(
    rules_with_remotes: list[tuple[frozenset, PortRuleKafka]],
) -> KafkaBatchModel | ConstVerdict:
    """Compile (allowed_remote_set, rule) rows into device arrays.  Rules
    must be sanitized (role expansion done, reference:
    api/kafka.go Sanitize)."""
    if not rules_with_remotes:
        return ConstVerdict(False)
    n = len(rules_with_remotes)
    api_key_mask = np.zeros((n, MAX_API_KEY), bool)
    version = np.zeros((n,), np.int32)
    version_any = np.zeros((n,), bool)
    client = np.zeros((n, MAX_CLIENT_LEN), np.uint8)
    client_len = np.zeros((n,), np.int32)
    client_any = np.zeros((n,), bool)
    topic = np.zeros((n, MAX_TOPIC_LEN), np.uint8)
    topic_len = np.zeros((n,), np.int32)
    topic_any = np.zeros((n,), bool)

    for i, (_, r) in enumerate(rules_with_remotes):
        if len(r.topic.encode()) > MAX_TOPIC_LEN:
            raise ValueError(f"rule topic exceeds {MAX_TOPIC_LEN} bytes")
        if len(r.client_id.encode()) > MAX_CLIENT_LEN:
            raise ValueError(f"rule clientID exceeds {MAX_CLIENT_LEN} bytes")
        if r.api_keys_int:
            for k in r.api_keys_int:
                if 0 <= k < MAX_API_KEY:
                    api_key_mask[i, k] = True
        else:
            api_key_mask[i, :] = True  # wildcard (CheckAPIKeyRole)
        v, wildcard = r.get_api_version()
        version[i] = v
        version_any[i] = wildcard
        client[i], client_len[i] = _pad_bytes(r.client_id, MAX_CLIENT_LEN)
        client_any[i] = r.client_id == ""
        topic[i], topic_len[i] = _pad_bytes(r.topic, MAX_TOPIC_LEN)
        topic_any[i] = r.topic == ""

    is_topic_key = np.zeros((MAX_API_KEY,), bool)
    for k in TOPIC_API_KEYS:
        if k < MAX_API_KEY:
            is_topic_key[k] = True

    packed_ids, any_remote = pack_remote_sets(
        [rs for rs, _ in rules_with_remotes]
    )
    return KafkaBatchModel(
        api_key_mask=jnp.asarray(api_key_mask),
        version=jnp.asarray(version),
        version_any=jnp.asarray(version_any),
        client=jnp.asarray(client),
        client_len=jnp.asarray(client_len),
        client_any=jnp.asarray(client_any),
        topic=jnp.asarray(topic),
        topic_len=jnp.asarray(topic_len),
        topic_any=jnp.asarray(topic_any),
        is_topic_key=jnp.asarray(is_topic_key),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
    )


@jax.tree_util.register_pytree_node_class
@dataclass
class KafkaRequestBatch:
    """Fixed-shape encoding of F parsed requests."""

    api_key: np.ndarray  # [F] int32
    api_version: np.ndarray  # [F] int32
    client: np.ndarray  # [F, MAX_CLIENT_LEN] uint8
    client_len: np.ndarray  # [F] int32
    topics: np.ndarray  # [F, MAX_TOPICS, MAX_TOPIC_LEN] uint8
    topic_len: np.ndarray  # [F, MAX_TOPICS] int32
    topic_count: np.ndarray  # [F] int32
    parsed: np.ndarray  # [F] bool
    overflow: np.ndarray  # [F] bool — exceeds tensor limits; host decides

    def tree_flatten(self):
        return (
            (self.api_key, self.api_version, self.client, self.client_len,
             self.topics, self.topic_len, self.topic_count, self.parsed,
             self.overflow),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def encode_requests(
    reqs: list[RequestMessage], topic_width: int | None = None
) -> KafkaRequestBatch:
    """Host-side tensorization of parsed requests; deduplicates topics
    (MatchesRule's map semantics — reference: policy.go:204-208).
    Requests exceeding the tensor limits are flagged ``overflow``: the
    device denies them and the caller re-evaluates with the host oracle
    (cilium_tpu.kafka.policy.matches_rule) — never a silent truncation.

    The topic tensor width auto-sizes to the batch's longest name,
    rounded up to a power-of-two bucket (min 32): real topic names are
    tens of bytes, and shipping [F, T, 256] mostly-padding tensors makes
    the batch transfer-bound (measured ~4x throughput loss)."""
    f = len(reqs)
    if topic_width is not None and topic_width > MAX_TOPIC_LEN:
        raise ValueError(f"topic_width exceeds {MAX_TOPIC_LEN}")
    if topic_width is None:
        longest = max(
            (len(t.encode()) for r in reqs for t in r.get_topics()),
            default=1,
        )
        topic_width = 32
        while topic_width < min(longest, MAX_TOPIC_LEN):
            topic_width *= 2
    batch = KafkaRequestBatch(
        api_key=np.zeros((f,), np.int32),
        api_version=np.zeros((f,), np.int32),
        client=np.zeros((f, MAX_CLIENT_LEN), np.uint8),
        client_len=np.zeros((f,), np.int32),
        topics=np.zeros((f, MAX_TOPICS, topic_width), np.uint8),
        topic_len=np.zeros((f, MAX_TOPICS), np.int32),
        topic_count=np.zeros((f,), np.int32),
        parsed=np.zeros((f,), bool),
        overflow=np.zeros((f,), bool),
    )
    for i, r in enumerate(reqs):
        batch.api_key[i] = r.api_key
        batch.api_version[i] = r.api_version
        distinct = list(dict.fromkeys(r.get_topics()))
        if (len(distinct) > MAX_TOPICS
                or not 0 <= r.api_key < MAX_API_KEY
                or len(r.client_id.encode()) > MAX_CLIENT_LEN
                or any(len(t.encode()) > topic_width for t in distinct)):
            batch.overflow[i] = True
            continue
        batch.client[i], batch.client_len[i] = _pad_bytes(
            r.client_id, MAX_CLIENT_LEN
        )
        batch.topic_count[i] = len(distinct)
        for t, name in enumerate(distinct):
            batch.topics[i, t], batch.topic_len[i, t] = _pad_bytes(
                name, topic_width
            )
        batch.parsed[i] = r.parsed and r.api_key in PARSED_TOPIC_KEYS
    return batch


def kafka_rule_hits(
    model: KafkaBatchModel, batch: KafkaRequestBatch, remotes
) -> tuple[jax.Array, jax.Array]:
    """Per-rule-set partial reductions: (simple [F] bool, cover [F, T]
    bool).  These OR across disjoint rule subsets, so rule-axis sharding
    psums them before the final combine (kafka_combine) — the combine
    itself (∀topics) does NOT distribute over rule subsets."""
    api_key = jnp.asarray(batch.api_key)
    api_version = jnp.asarray(batch.api_version)
    client = jnp.asarray(batch.client)
    client_len = jnp.asarray(batch.client_len)
    topics = jnp.asarray(batch.topics)
    topic_len = jnp.asarray(batch.topic_len)
    topic_count = jnp.asarray(batch.topic_count)
    parsed = jnp.asarray(batch.parsed)
    remotes = jnp.asarray(remotes, jnp.int32)

    key_clamped = jnp.clip(api_key, 0, MAX_API_KEY - 1)
    in_range = (api_key >= 0) & (api_key < MAX_API_KEY)

    # [F, R] api-key role + version gates (policy.go:152-159).
    key_ok = model.api_key_mask[:, :].T[key_clamped] & in_range[:, None]
    ver_ok = model.version_any[None, :] | (
        model.version[None, :] == api_version[:, None]
    )

    # clientID equality [F, R]: lengths equal and padded bytes equal.
    client_eq = (client_len[:, None] == model.client_len[None, :]) & jnp.all(
        client[:, None, :] == model.client[None, :, :], axis=-1
    )

    # Per-request-type extra gate (ruleMatches switch, policy.go:161-195).
    simple_rule = model.topic_any & model.client_any  # no extra conditions
    is_fc = api_key == FIND_COORDINATOR_KEY
    nil_topic_reject = (~model.topic_any[None, :]) & (
        model.is_topic_key[key_clamped] & in_range
    )[:, None]
    extra = jnp.where(
        simple_rule[None, :],
        True,
        jnp.where(
            parsed[:, None],
            model.client_any[None, :] | client_eq,
            jnp.where(is_fc[:, None], True, ~nil_topic_reject),
        ),
    )

    rok = remote_ok(remotes, model.remote_ids, model.any_remote)  # [F, R]
    base = key_ok & ver_ok & extra & rok  # [F, R]

    # First branch: topic-less rule OR topic-less request (policy.go:210).
    simple = jnp.any(
        base & (model.topic_any[None, :] | (topic_count == 0)[:, None]),
        axis=1,
    )

    # Topic coverage: [F, T, R] exact compares.  The rule topic tensor is
    # stored at MAX_TOPIC_LEN but the batch auto-sizes its width (see
    # encode_requests); slice the rule tensor down to the batch width.
    # Bit-identical: batch topic lengths are always <= width, so a rule
    # with topic_len > width already fails the length-equality gate, and
    # for rules with topic_len <= width every meaningful byte lies inside
    # the slice (both tensors are zero-padded past their length).
    rule_topic = model.topic[:, : topics.shape[-1]]
    t_eq = (topic_len[:, :, None] == model.topic_len[None, None, :]) & jnp.all(
        topics[:, :, None, :] == rule_topic[None, None, :, :], axis=-1
    )
    cover = jnp.any(
        t_eq & (~model.topic_any)[None, None, :] & base[:, None, :], axis=2
    )  # [F, T]
    return simple, cover


def kafka_combine(
    simple: jax.Array,  # [F] bool — ORed across rule subsets
    cover: jax.Array,  # [F, T] bool — ORed across rule subsets
    topic_count: jax.Array,  # [F] int32
    overflow: jax.Array,  # [F] bool
) -> jax.Array:
    """Final verdict from (possibly psum-merged) partial reductions."""
    t_idx = jnp.arange(cover.shape[1])[None, :]
    active = t_idx < topic_count[:, None]
    all_covered = jnp.all(cover | ~active, axis=1) & (topic_count > 0)
    # Overflowed requests are denied on device; the engine re-evaluates
    # them with the host oracle.
    return (simple | all_covered) & ~overflow


@jax.jit
def kafka_verdicts(
    model: KafkaBatchModel, batch: KafkaRequestBatch, remotes
):
    """Returns allowed [F] bool; bit-identical to matches_rule."""
    simple, cover = kafka_rule_hits(model, batch, remotes)
    return kafka_combine(
        simple,
        cover,
        jnp.asarray(batch.topic_count),
        jnp.asarray(batch.overflow),
    )
