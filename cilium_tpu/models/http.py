"""HTTP batch verdict model: request-line + header policy on device.

Replaces the reference's per-request std::regex walk in the Envoy filter
(reference: envoy/cilium_l7policy.cc:51 + cilium_network_policy.h:50-76
HttpNetworkPolicyRule: anchored regex on path/method/host, exact header
presence) and the agent-side rule model (reference:
pkg/policy/api/http.go:28 PortRuleHTTP) with one device pass:

  1. tokenize the request line ([F, L] uint8): method span = [0, sp1),
     path span = (sp1, sp2) — pure bytescan, no host round-trip
  2. TIERED method/path matching:
       - tier 0 (free): omitted fields allow everything (http.go skips
         the check entirely) — a per-rule flag, no byte work
       - tier 1 (literal): patterns that are literals ("GET"),
         alternations of literals ("GET|HEAD"), or literal prefixes
         ("/api/v1/.*") — the overwhelming majority of real policies —
         match with vectorized byte compares, NO automaton at all
       - tier 2 (regex): everything else goes through the NFA (matmul,
         small sets) or per-pattern DFA (block-diagonal, large sets)
  3. host regex + exact header lines matched as CRLF-delimited patterns
     searched over the whole request head
  4. a rule allows iff all its present components match; request allowed
     iff any rule with a matching remote allows.

The tiers are bit-identical to the pure-regex path: literal analysis is
done on the parsed AST (so escapes and alternation mirror the compiler),
and literal-prefix rows carry the regex ``.*``-excludes-newline guard.

Deny maps to a 403 response injected by the runtime engine
(reference: cilium_l7policy.cc 403 body injection).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bytescan import first_occurrence, first_subsequence2, spans_equal_prefix, spans_start_with
from ..ops.dfa import DeviceDfa, dfa_search_spans
from ..ops.nfa import DeviceNfa, device_nfa, nfa_search_spans
from ..policy.api import PortRuleHTTP
from ..regex import compile_patterns
from ..regex.parse import DOT_BYTES, ParseError, parse
from .base import ConstVerdict, first_match, pack_remote_sets, remote_ok

_RE_META = set("\\^$.[]|()*+?{}")

LIT_W = 64  # max literal needle bytes; longer literals fall to regex


def re_escape(s: str) -> str:
    """Escape a literal for the POSIX-extended regex compiler."""
    return "".join("\\" + c if c in _RE_META else c for c in s)


def _ci_literal(s: str) -> str:
    """Case-insensitive regex for a literal (header field names are
    case-insensitive, RFC 9110)."""
    out = []
    for c in s:
        if c.isalpha():
            out.append(f"[{c.upper()}{c.lower()}]")
        elif c in _RE_META:
            out.append("\\" + c)
        else:
            out.append(c)
    return "".join(out)


def _header_pattern(header: str) -> str:
    """'Name: value' -> CRLF-framed pattern with case-insensitive name and
    optional OWS around the value (matching the Host handling and the
    reference's case-insensitive header lookup)."""
    name, sep, value = header.partition(":")
    if not sep:
        return "\r\n" + re_escape(header) + "\r\n"
    return (
        "\r\n" + _ci_literal(name) + ":[ \t]*"
        + re_escape(value.strip()) + "[ \t]*\r\n"
    )


# --- literal-tier analysis ------------------------------------------------

def _ast_literal(node) -> bytes | None:
    """Bytes of a pure single-byte-literal concatenation, else None."""
    kind = node[0]
    if kind == "empty":
        return b""
    if kind == "lit":
        s = node[1]
        return bytes([next(iter(s))]) if len(s) == 1 else None
    if kind == "cat":
        parts = [_ast_literal(x) for x in node[1]]
        if any(p is None for p in parts):
            return None
        return b"".join(parts)
    return None


def _ast_dotstar(node) -> bool:
    return node[0] == "star" and node[1][0] == "lit" and node[1][1] == DOT_BYTES


def analyze_literal(pattern: str):
    """Classify a rule field pattern for the literal tier.

    Returns ("any", None) — omitted field, no constraint;
            ("lits", [bytes, ...]) — full match any of the literals;
            ("prefix", bytes) — literal then ``.*`` (newline-guarded);
            None — general regex (tier 2).
    The analysis runs on the parsed AST so escaping/alternation exactly
    mirror the regex compiler's reading of the pattern."""
    if pattern == "":
        return ("any", None)
    try:
        ast = parse(pattern)
    except ParseError:
        return None  # surface the error via the regex compiler
    lit = _ast_literal(ast)
    if lit is not None:
        return ("lits", [lit]) if len(lit) <= LIT_W else None
    if _ast_dotstar(ast):
        return ("prefix", b"")
    if ast[0] == "cat" and len(ast[1]) >= 2 and _ast_dotstar(ast[1][-1]):
        head = (
            ast[1][0] if len(ast[1]) == 2 else ("cat", ast[1][:-1])
        )
        lit = _ast_literal(head)
        if lit is not None and len(lit) <= LIT_W:
            return ("prefix", lit)
    if ast[0] == "alt":
        outs = [_ast_literal(b) for b in ast[1]]
        if all(o is not None and len(o) <= LIT_W for o in outs):
            return ("lits", outs)
    return None


def analyze_rules(
    rules_with_remotes: list, tiers_on: bool = True
) -> tuple:
    """Classify every rule's method/path into the literal or regex tier
    and collect host/header patterns.  Shared by build_http_model and
    the rule-axis sharded builder (parallel/rulesharding.py)."""
    r = len(rules_with_remotes)
    m_rows: list[tuple[bytes, bool, int]] = []  # (needle, prefix, rule)
    p_rows: list[tuple[bytes, bool, int]] = []
    line_patterns: list[str] = []
    line_rule: list[int] = []
    line_slot: list[int] = []
    method_any = np.zeros((r,), bool)
    path_any = np.zeros((r,), bool)
    head_patterns: list[str] = []
    head_rule: list[int] = []
    head_count: list[int] = []

    for i, (_, h) in enumerate(rules_with_remotes):
        for slot, field in ((0, h.method), (1, h.path)):
            kind = analyze_literal(field) if tiers_on else (
                ("any", None) if field == "" else None
            )
            if kind is None:
                # Anchored full matches (Envoy regex_match semantics,
                # cilium_network_policy.h:50).
                line_patterns.append(f"^({field})$" if field else "^.*$")
                line_rule.append(i)
                line_slot.append(slot)
            elif kind[0] == "any":
                (method_any if slot == 0 else path_any)[i] = True
            elif kind[0] == "lits":
                rows = m_rows if slot == 0 else p_rows
                for lit in kind[1]:
                    rows.append((lit, False, i))
            else:  # prefix
                rows = m_rows if slot == 0 else p_rows
                rows.append((kind[1], True, i))
        n_head = 0
        if h.host:
            # Field names are case-insensitive and OWS after ':' is
            # optional (RFC 9110); match any casing and whitespace run.
            head_patterns.append(
                f"\r\n[Hh][Oo][Ss][Tt]:[ \t]*({h.host})[ \t]*\r\n"
            )
            head_rule.append(i)
            n_head += 1
        for header in h.headers:
            head_patterns.append(_header_pattern(header))
            head_rule.append(i)
            n_head += 1
        head_count.append(n_head)
    return (m_rows, p_rows, line_patterns, line_rule, line_slot,
            method_any, path_any, head_patterns, head_rule, head_count)


def lit_arrays(rows: list, n_pad: int | None = None,
               width: int | None = None):
    """Pack (needle, prefix, rule) literal rows into device-ready numpy
    arrays, padded to ``n_pad`` rows (dead rows have live=False).  The
    needle width is trimmed to the longest actual needle (rounded up to
    8, min 8) — the span-compare window build scales with it; pass
    ``width`` to unify shapes across shards."""
    n = max(len(rows), 1) if n_pad is None else n_pad
    if width is None:
        max_len = max((len(lit) for lit, _, _ in rows), default=0)
        width = min(LIT_W, max(8, (max_len + 7) // 8 * 8))
    w = width
    needle = np.zeros((n, w), np.uint8)
    nlen = np.zeros((n,), np.int32)
    prefix = np.zeros((n,), bool)
    rule = np.zeros((n,), np.int32)
    live = np.zeros((n,), bool)
    for k, (lit, pfx, ri) in enumerate(rows):
        needle[k, : len(lit)] = np.frombuffer(lit, np.uint8)
        nlen[k] = len(lit)
        prefix[k] = pfx
        rule[k] = ri
        live[k] = True
    return needle, nlen, prefix, rule, live


@jax.tree_util.register_pytree_node_class
@dataclass
class HttpBatchModel:
    # tier 1: literal method (slot m) / path (slot p) rows
    m_needle: jax.Array  # [Nm, LIT_W] uint8
    m_len: jax.Array  # [Nm] int32
    m_prefix: jax.Array  # [Nm] bool
    m_rule: jax.Array  # [Nm] int32
    m_live: jax.Array  # [Nm] bool (False = padding row)
    p_needle: jax.Array  # [Np, LIT_W] uint8
    p_len: jax.Array  # [Np] int32
    p_prefix: jax.Array  # [Np] bool
    p_rule: jax.Array  # [Np] int32
    p_live: jax.Array  # [Np] bool
    method_any: jax.Array  # [R] bool — field omitted
    path_any: jax.Array  # [R] bool
    # tier 2: general regex line patterns (anchored), slot-tagged
    line_nfa: "DeviceNfa | DeviceDfa | None"
    line_rule: jax.Array  # [PL] int32
    line_slot: jax.Array  # [PL] int32 — 0 method, 1 path
    # host/header patterns over the request head
    head_nfa: "DeviceNfa | DeviceDfa | None"
    head_rule: jax.Array  # [P] int32 — owning rule row
    head_count: jax.Array  # [R] int32 — head patterns per rule
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool
    n_rules: int = 0
    # Static slot usage (trace-time): which spans the regex tier must
    # actually search — an all-path pattern set skips the method-span
    # automaton pass entirely (half the regex-tier cost).
    has_method_rx: bool = False
    has_path_rx: bool = False
    # Per-rule compiled match kind (literal|regex|nfa) — static aux for
    # rule attribution labels, never device data.
    match_kinds: tuple = ()
    # Per-rule (remote_set_or_None, byte_free) reduction for the verdict
    # cache's byte-invariance analysis (policy/invariance.py) — host
    # aux like match_kinds, never device data, never a pytree leaf.
    invariant_rows: tuple = ()

    def tree_flatten(self):
        return (
            (self.m_needle, self.m_len, self.m_prefix, self.m_rule,
             self.m_live, self.p_needle, self.p_len, self.p_prefix,
             self.p_rule, self.p_live, self.method_any, self.path_any,
             self.line_nfa, self.line_rule, self.line_slot,
             self.head_nfa, self.head_rule, self.head_count,
             self.remote_ids, self.any_remote),
            (self.n_rules, self.has_method_rx, self.has_path_rx,
             self.match_kinds),
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(
            *leaves, n_rules=aux[0],
            has_method_rx=aux[1], has_path_rx=aux[2],
            match_kinds=aux[3] if len(aux) > 3 else (),
        )

    def __call__(self, data, lengths, remotes):
        return http_verdicts(self, data, lengths, remotes)

    def verdicts_attr(self, data, lengths, remotes):
        return http_verdicts_attr(self, data, lengths, remotes)


def _reduce_http_rows(rules_with_remotes) -> tuple:
    from ..policy.invariance import reduce_http_rows

    return reduce_http_rows(rules_with_remotes)


def build_http_model(
    rules_with_remotes: list[tuple[frozenset, PortRuleHTTP]],
    backend: str = "auto",
) -> HttpBatchModel | ConstVerdict:
    """Compile (allowed_remote_set, PortRuleHTTP) rows into device tables.

    Empty fields wildcard (reference: http.go — omitted fields allow all).
    ``backend`` governs the REGEX tier only: "nfa" (dense matmul),
    "dfa" (per-pattern gatherless blocks), "auto" (DFA-first at any
    size, NFA fallback on determinization blowup), or
    "regex-only" (disable the literal tier — every pattern through the
    automaton; used by parity tests)."""
    if not rules_with_remotes:
        return ConstVerdict(False)

    r = len(rules_with_remotes)
    rx_backend = "auto" if backend == "regex-only" else backend
    rows = analyze_rules(rules_with_remotes, tiers_on=backend != "regex-only")
    (m_rows, p_rows, line_patterns, line_rule, line_slot, method_any,
     path_any, head_patterns, head_rule, head_count) = rows

    packed_ids, any_remote = pack_remote_sets(
        [rs for rs, _ in rules_with_remotes]
    )

    mn, ml, mp, mr, mlive = lit_arrays(m_rows)
    pn, pl_, pp, pr, plive = lit_arrays(p_rows)

    line_tab = _compile_line_tables(line_patterns, rx_backend)
    head_tab = _compile_line_tables(head_patterns, rx_backend)

    def _tier(tab) -> str:
        from ..ops.nfa import DeviceNfa as _Nfa

        return "nfa" if isinstance(tab, _Nfa) else "regex"

    # Per-rule match kind for attribution: a rule is "literal" when its
    # method/path resolved to tier 0/1 and it carries no head patterns;
    # any automaton involvement labels it by that automaton's backend
    # ("nfa" dense matmul / "regex" per-pattern DFA), nfa winning when
    # a rule touches both tables.
    kinds = ["literal"] * r
    for i in line_rule:
        kinds[i] = _tier(line_tab)
    for i in head_rule:
        if kinds[i] != "nfa":
            kinds[i] = _tier(head_tab)

    return HttpBatchModel(
        m_needle=jnp.asarray(mn),
        m_len=jnp.asarray(ml),
        m_prefix=jnp.asarray(mp),
        m_rule=jnp.asarray(mr),
        m_live=jnp.asarray(mlive),
        p_needle=jnp.asarray(pn),
        p_len=jnp.asarray(pl_),
        p_prefix=jnp.asarray(pp),
        p_rule=jnp.asarray(pr),
        p_live=jnp.asarray(plive),
        method_any=jnp.asarray(method_any),
        path_any=jnp.asarray(path_any),
        line_nfa=line_tab,
        line_rule=jnp.asarray(np.asarray(line_rule, np.int32)),
        line_slot=jnp.asarray(np.asarray(line_slot, np.int32)),
        head_nfa=head_tab,
        head_rule=jnp.asarray(np.asarray(head_rule, np.int32).reshape(-1)),
        head_count=jnp.asarray(np.asarray(head_count, np.int32)),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
        n_rules=r,
        has_method_rx=any(s == 0 for s in line_slot),
        has_path_rx=any(s == 1 for s in line_slot),
        match_kinds=tuple(kinds),
        invariant_rows=_reduce_http_rows(rules_with_remotes),
    )


def _compile_line_tables(patterns: list[str], backend: str):
    """Compile regex-tier patterns with the requested backend; None when
    the tier is empty.  DFA-first at every size since the integer-id
    step rewrite (ops/dfa.py) made the DFA ~12× the dense NFA."""
    from ..ops.rxsearch import compile_automaton

    if backend == "nfa":
        return device_nfa(compile_patterns(patterns)) if patterns else None
    return compile_automaton(patterns, backend)


def _first_occurrence_after(data, start, end, byte):
    """First ``byte`` at position > start and < end, else end."""
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    valid = (pos > start[:, None]) & (pos < end[:, None])
    hit = (data == jnp.uint8(byte)) & valid
    return jnp.min(jnp.where(hit, pos, end[:, None]), axis=1)


def _last_in_span(data, start, end, byte):
    """Last ``byte`` at position >= start and < end, else -1."""
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    valid = (pos >= start[:, None]) & (pos < end[:, None])
    hit = (data == jnp.uint8(byte)) & valid
    return jnp.max(jnp.where(hit, pos, jnp.int32(-1)), axis=1)


def _lit_hits(data, start, end, needle, nlen, prefix, live):
    """[F, N] literal-row hits on the span: exact rows need span == lit,
    prefix rows need span startswith lit AND no newline in the ``.*``
    remainder (regex ``.`` excludes \\n).  "No newline in the remainder"
    is exactly "the LAST span newline, if any, lies inside the needle
    bytes" — needle-internal newlines were matched literally."""
    exact = spans_equal_prefix(data, start, end, needle, nlen)
    starts = spans_start_with(data, start, end, needle, nlen)
    last_nl = _last_in_span(data, start, end, 0x0A)  # [F]
    no_nl_after = last_nl[:, None] < start[:, None] + nlen[None, :]
    hit = jnp.where(prefix[None, :], starts & no_nl_after, exact)
    return hit & live[None, :]


def _scatter_or(hits, rule_idx, n_rules):
    """[F, N] bool hits keyed by rule -> [F, R] bool any-hit."""
    f = hits.shape[0]
    counts = jnp.zeros((f, n_rules), jnp.int32)
    counts = counts.at[:, rule_idx].add(hits.astype(jnp.int32))
    return counts > 0


def _http_rule_hits(
    model: HttpBatchModel,
    data: jax.Array,  # [F, L] uint8 — complete request heads
    lengths: jax.Array,  # [F] int32 — head length incl. final CRLFCRLF
    remotes: jax.Array,  # [F] int32
):
    """Shared tokenize/tier pass; returns (complete [F] bool, head_len
    [F] int32, hits [F, R] bool) — the per-rule-row hit matrix both
    reductions (any-allow and first-match attribution) consume."""
    lengths = jnp.asarray(lengths, jnp.int32)
    remotes = jnp.asarray(remotes, jnp.int32)
    r = model.n_rules
    f = data.shape[0]

    # Head completeness: first CRLFCRLF.
    crlf2 = _first_crlfcrlf(data, lengths)
    complete = crlf2 < lengths
    head_len = crlf2 + 4

    # Request line tokenize.
    line_end = first_subsequence2(data, lengths, 0x0D, 0x0A)  # [F]
    sp1 = first_occurrence(data, line_end, 0x20)
    sp2 = _first_occurrence_after(data, sp1, line_end, 0x20)
    m_start, m_end = jnp.zeros_like(sp1), sp1
    p_start, p_end = sp1 + 1, sp2

    # Tier 0/1: wildcard flags + literal rows.
    method_ok = model.method_any[None, :] | _scatter_or(
        _lit_hits(data, m_start, m_end, model.m_needle, model.m_len,
                  model.m_prefix, model.m_live),
        model.m_rule, r,
    )
    path_ok = model.path_any[None, :] | _scatter_or(
        _lit_hits(data, p_start, p_end, model.p_needle, model.p_len,
                  model.p_prefix, model.p_live),
        model.p_rule, r,
    )

    # Tier 2: leftover regex patterns, evaluated on both spans and
    # routed by slot.  (Resolved at trace time; absent for pure-literal
    # rule sets — the common case.)
    if model.line_nfa is not None:
        search = (
            dfa_search_spans
            if isinstance(model.line_nfa, DeviceDfa)
            else nfa_search_spans
        )
        is_m = model.line_slot == 0
        if model.has_method_rx:
            rx_m = search(model.line_nfa, data, m_start, m_end)  # [F, PL]
            method_ok = method_ok | _scatter_or(
                rx_m & is_m[None, :], model.line_rule, r
            )
        if model.has_path_rx:
            rx_p = search(model.line_nfa, data, p_start, p_end)
            path_ok = path_ok | _scatter_or(
                rx_p & ~is_m[None, :], model.line_rule, r
            )

    # Host/header patterns searched over the head region starting at the
    # request line's CRLF (so every header line is CRLF-framed).
    if model.head_nfa is not None:
        head_search = (
            dfa_search_spans
            if isinstance(model.head_nfa, DeviceDfa)
            else nfa_search_spans
        )
        h_hits = head_search(
            model.head_nfa, data, line_end, head_len - 2
        )  # [F, P]
        # all-of per rule: count matches per rule == head_count
        per_rule = jnp.zeros((h_hits.shape[0], r), jnp.int32)
        per_rule = per_rule.at[:, model.head_rule].add(
            h_hits.astype(jnp.int32)
        )
        head_ok = per_rule >= model.head_count[None, :]
    else:
        head_ok = jnp.ones((f, r), bool)

    rok = remote_ok(remotes, model.remote_ids, model.any_remote)
    return complete, head_len, method_ok & path_ok & head_ok & rok


@jax.jit
def http_verdicts(
    model: HttpBatchModel,
    data: jax.Array,  # [F, L] uint8 — complete request heads
    lengths: jax.Array,  # [F] int32 — head length incl. final CRLFCRLF
    remotes: jax.Array,  # [F] int32
):
    """Returns (complete [F] bool, head_len [F] int32, allow [F] bool)."""
    complete, head_len, hits = _http_rule_hits(model, data, lengths, remotes)
    allow = jnp.any(hits, axis=1)
    return complete, head_len, allow & complete


@jax.jit
def http_verdicts_attr(
    model: HttpBatchModel,
    data: jax.Array,
    lengths: jax.Array,
    remotes: jax.Array,
):
    """http_verdicts plus the deciding rule row: (complete, head_len,
    allow, rule [F] int32).  ``rule`` is the FIRST matching rule row in
    the host oracle's walk order (exact-port rules then wildcard, one
    row per (rule, matcher) — build_http_model_for_port's flattening),
    or -1 where not allowed; an argmax over the same hit matrix in the
    same fused pass."""
    complete, head_len, hits = _http_rule_hits(model, data, lengths, remotes)
    allow = jnp.any(hits, axis=1) & complete
    return complete, head_len, allow, first_match(hits, allow)


def _first_crlfcrlf(data: jax.Array, lengths: jax.Array) -> jax.Array:
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]

    def shifted(k):
        return jnp.concatenate(
            [data[:, k:], jnp.zeros((f, k), dtype=data.dtype)], axis=1
        )

    hit = (
        (data == 0x0D)
        & (shifted(1) == 0x0A)
        & (shifted(2) == 0x0D)
        & (shifted(3) == 0x0A)
        & ((pos + 3) < lengths[:, None])
    )
    return jnp.min(jnp.where(hit, pos, lengths[:, None]), axis=1)


def collect_http_rows(policy, ingress: bool, port: int):
    """Resolve the effective (remote_set, PortRuleHTTP) rows for
    (policy, direction, port), applying the reference's port cascade
    (exact port OR wildcard 0) — the HTTP twin of
    models/r2d2.collect_policy_rows.  Returns a ConstVerdict for the
    degenerate cases; exposed so rule-axis sharding can split the rows
    in the same flattened walk order the attribution contract names."""
    from ..proxylib.parsers.http import HttpRule

    if policy is None:
        return ConstVerdict(False)
    side = policy.ingress if ingress else policy.egress
    rows: list[tuple[frozenset, PortRuleHTTP]] = []
    for key in (port, 0):
        rules = side.by_port.get(key)
        if rules is None:
            continue
        if not rules.have_l7_rules or not rules.rules:
            return ConstVerdict(True)
        for rule in rules.rules:
            matchers = rule.l7_matchers or [None]
            for m in matchers:
                if m is None:
                    rows.append((rule.allowed_remotes, PortRuleHTTP()))
                else:
                    assert isinstance(m, HttpRule), f"not an http rule: {m!r}"
                    rows.append(
                        (
                            rule.allowed_remotes,
                            PortRuleHTTP(
                                method=m.method_src, path=m.path_src,
                                host=m.host_src, headers=list(m.headers),
                            ),
                        )
                    )
    if not rows:
        return ConstVerdict(False)
    return rows


def build_http_model_for_port(policy, ingress: bool, port: int,
                              backend: str = "auto"):
    """Compile the effective HTTP rule rows for (policy, direction,
    port) from a proxylib PolicyInstance — used by the sidecar's
    engine bind (see collect_http_rows for the cascade semantics)."""
    rows = collect_http_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    return build_http_model(rows, backend=backend)
