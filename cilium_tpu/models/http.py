"""HTTP batch verdict model: request-line + header policy on device.

Replaces the reference's per-request std::regex walk in the Envoy filter
(reference: envoy/cilium_l7policy.cc:51 + cilium_network_policy.h:50-76
HttpNetworkPolicyRule: anchored regex on path/method/host, exact header
presence) and the agent-side rule model (reference:
pkg/policy/api/http.go:28 PortRuleHTTP) with one device pass:

  1. tokenize the request line ([F, L] uint8): method span = [0, sp1),
     path span = (sp1, sp2) — pure bytescan, no host round-trip
  2. anchored NFA match of per-rule method/path regexes on those spans
  3. host regex + exact header lines matched as CRLF-delimited patterns
     searched over the whole request head
  4. a rule allows iff all its present components match; request allowed
     iff any rule with a matching remote allows.

Deny maps to a 403 response injected by the runtime engine
(reference: cilium_l7policy.cc 403 body injection).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bytescan import first_occurrence, first_subsequence2
from ..ops.nfa import DeviceNfa, device_nfa, nfa_search_spans
from ..policy.api import PortRuleHTTP
from ..regex import compile_patterns
from .base import ConstVerdict, pack_remote_sets, remote_ok

_RE_META = set("\\^$.[]|()*+?{}")


def re_escape(s: str) -> str:
    """Escape a literal for the POSIX-extended regex compiler."""
    return "".join("\\" + c if c in _RE_META else c for c in s)


def _ci_literal(s: str) -> str:
    """Case-insensitive regex for a literal (header field names are
    case-insensitive, RFC 9110)."""
    out = []
    for c in s:
        if c.isalpha():
            out.append(f"[{c.upper()}{c.lower()}]")
        elif c in _RE_META:
            out.append("\\" + c)
        else:
            out.append(c)
    return "".join(out)


def _header_pattern(header: str) -> str:
    """'Name: value' -> CRLF-framed pattern with case-insensitive name and
    optional OWS around the value (matching the Host handling and the
    reference's case-insensitive header lookup)."""
    name, sep, value = header.partition(":")
    if not sep:
        return "\r\n" + re_escape(header) + "\r\n"
    return (
        "\r\n" + _ci_literal(name) + ":[ \t]*"
        + re_escape(value.strip()) + "[ \t]*\r\n"
    )


@jax.tree_util.register_pytree_node_class
@dataclass
class HttpBatchModel:
    line_nfa: DeviceNfa  # method+path patterns (anchored), 2 per rule
    head_nfa: DeviceNfa | None  # host/header patterns over the head
    # Mapping from flattened head patterns to rules:
    head_rule: jax.Array  # [P] int32 — owning rule row
    head_count: jax.Array  # [R] int32 — number of head patterns per rule
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool
    n_rules: int = 0

    def tree_flatten(self):
        return (
            (self.line_nfa, self.head_nfa, self.head_rule, self.head_count,
             self.remote_ids, self.any_remote),
            (self.n_rules,),
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, n_rules=aux[0])

    def __call__(self, data, lengths, remotes):
        return http_verdicts(self, data, lengths, remotes)


def build_http_model(
    rules_with_remotes: list[tuple[frozenset, PortRuleHTTP]],
) -> HttpBatchModel | ConstVerdict:
    """Compile (allowed_remote_set, PortRuleHTTP) rows into device NFAs.

    Empty fields wildcard (reference: http.go — omitted fields allow all).
    """
    if not rules_with_remotes:
        return ConstVerdict(False)

    line_patterns: list[str] = []
    head_patterns: list[str] = []
    head_rule: list[int] = []
    head_count: list[int] = []

    for i, (_, h) in enumerate(rules_with_remotes):
        # Anchored full matches (Envoy regex_match semantics,
        # cilium_network_policy.h:50).
        line_patterns.append(f"^({h.method})$" if h.method else "^.*$")
        line_patterns.append(f"^({h.path})$" if h.path else "^.*$")
        n_head = 0
        if h.host:
            # Field names are case-insensitive and OWS after ':' is
            # optional (RFC 9110); match any casing and whitespace run.
            head_patterns.append(
                f"\r\n[Hh][Oo][Ss][Tt]:[ \t]*({h.host})[ \t]*\r\n"
            )
            head_rule.append(i)
            n_head += 1
        for header in h.headers:
            head_patterns.append(_header_pattern(header))
            head_rule.append(i)
            n_head += 1
        head_count.append(n_head)

    r = len(rules_with_remotes)
    packed_ids, any_remote = pack_remote_sets(
        [rs for rs, _ in rules_with_remotes]
    )
    return HttpBatchModel(
        line_nfa=device_nfa(compile_patterns(line_patterns)),
        head_nfa=(
            device_nfa(compile_patterns(head_patterns))
            if head_patterns
            else None
        ),
        head_rule=jnp.asarray(np.asarray(head_rule, np.int32).reshape(-1)),
        head_count=jnp.asarray(np.asarray(head_count, np.int32)),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
        n_rules=r,
    )


def _first_occurrence_after(data, start, end, byte):
    """First ``byte`` at position > start and < end, else end."""
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    valid = (pos > start[:, None]) & (pos < end[:, None])
    hit = (data == jnp.uint8(byte)) & valid
    return jnp.min(jnp.where(hit, pos, end[:, None]), axis=1)


@jax.jit
def http_verdicts(
    model: HttpBatchModel,
    data: jax.Array,  # [F, L] uint8 — complete request heads
    lengths: jax.Array,  # [F] int32 — head length incl. final CRLFCRLF
    remotes: jax.Array,  # [F] int32
):
    """Returns (complete [F] bool, head_len [F] int32, allow [F] bool)."""
    lengths = jnp.asarray(lengths, jnp.int32)
    remotes = jnp.asarray(remotes, jnp.int32)

    # Head completeness: first CRLFCRLF.
    crlf2 = _first_crlfcrlf(data, lengths)
    complete = crlf2 < lengths
    head_len = crlf2 + 4

    # Request line tokenize.
    line_end = first_subsequence2(data, lengths, 0x0D, 0x0A)  # [F]
    sp1 = first_occurrence(data, line_end, 0x20)
    sp2 = _first_occurrence_after(data, sp1, line_end, 0x20)

    # Anchored method/path matches: [F, 2R].
    m_hits = nfa_search_spans(model.line_nfa, data, jnp.zeros_like(sp1), sp1)
    p_hits = nfa_search_spans(model.line_nfa, data, sp1 + 1, sp2)
    r = model.n_rules
    idx = jnp.arange(r)
    method_ok = m_hits[:, idx * 2]
    path_ok = p_hits[:, idx * 2 + 1]

    # Host/header patterns searched over the head region starting at the
    # request line's CRLF (so every header line is CRLF-framed).
    if model.head_nfa is not None:
        h_hits = nfa_search_spans(
            model.head_nfa, data, line_end, head_len - 2
        )  # [F, P]
        # all-of per rule: count matches per rule == head_count
        per_rule = jnp.zeros((h_hits.shape[0], r), jnp.int32)
        per_rule = per_rule.at[:, model.head_rule].add(
            h_hits.astype(jnp.int32)
        )
        head_ok = per_rule >= model.head_count[None, :]
    else:
        head_ok = jnp.ones((data.shape[0], r), bool)

    rok = remote_ok(remotes, model.remote_ids, model.any_remote)
    allow = jnp.any(method_ok & path_ok & head_ok & rok, axis=1)
    return complete, head_len, allow & complete


def _first_crlfcrlf(data: jax.Array, lengths: jax.Array) -> jax.Array:
    f, l = data.shape
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]

    def shifted(k):
        return jnp.concatenate(
            [data[:, k:], jnp.zeros((f, k), dtype=data.dtype)], axis=1
        )

    hit = (
        (data == 0x0D)
        & (shifted(1) == 0x0A)
        & (shifted(2) == 0x0D)
        & (shifted(3) == 0x0A)
        & ((pos + 3) < lengths[:, None])
    )
    return jnp.min(jnp.where(hit, pos, lengths[:, None]), axis=1)
