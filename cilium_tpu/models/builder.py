"""Build device verdict models from resolved L4 policy filters.

The bridge from the policy engine's output (L4Filter with per-selector
L7 rules, reference: pkg/policy/l4.go L7DataMap) to the device models:
selectors are expanded against the identity cache into allowed-remote
sets (the same expansion the reference does when pushing NPDS policy to
proxies, reference: pkg/envoy/server.go:607 getNetworkPolicy), and the
rules compile into the per-protocol batch model.
"""

from __future__ import annotations

from ..policy.api import PortRuleHTTP, PortRuleKafka
from ..policy.l4 import L4Filter, PARSER_TYPE_HTTP, PARSER_TYPE_KAFKA
from .base import ConstVerdict
from .http import build_http_model
from .kafka import build_kafka_model


def expand_selector_remotes(sel, identity_cache: dict) -> frozenset | None:
    """Identities whose labels the selector matches.  None means wildcard
    (any remote); an empty frozenset means the selector currently matches
    NO identity — callers must drop such rows, not wildcard them."""
    if sel.is_wildcard():
        return None
    return frozenset(
        numeric
        for numeric, lbls in identity_cache.items()
        if sel.matches(lbls.to_array())
    )


def _remote_rows(sel, identity_cache: dict) -> list[frozenset] | None:
    """Resolve a selector to pack_remote_sets-convention sets (empty set =
    wildcard), chunked so no set exceeds MAX_REMOTES (broad selectors
    split into several rows).  None means the row must be skipped (fail
    closed: a selector matching no known identity allows nobody)."""
    from .base import MAX_REMOTES

    remotes = expand_selector_remotes(sel, identity_cache)
    if remotes is None:
        return [frozenset()]  # wildcard
    if not remotes:
        return None  # matches nothing: skip
    ordered = sorted(remotes)
    return [
        frozenset(ordered[i:i + MAX_REMOTES])
        for i in range(0, len(ordered), MAX_REMOTES)
    ]


def build_model_for_filter(f: L4Filter, identity_cache: dict, mesh=None):
    """Compile an L4Filter's L7 rules into a device batch model.

    Returns a model callable or ConstVerdict.  Generic (l7proto) rules are
    served by the proxylib parser pipeline instead (cilium_tpu.proxylib),
    mirroring the reference's dispatch (pkg/proxy/proxy.go:229-236).
    With a (flows, rules) ``mesh``, rule rows shard across RULE_AXIS and
    the returned model is the mesh-resident wrapper (same call contract,
    single-chip fallback attached for the device-loss rung).
    """
    if f.l7_parser == PARSER_TYPE_HTTP:
        rows: list[tuple[frozenset, PortRuleHTTP]] = []
        for sel, l7 in f.l7_rules_per_ep.items():
            remote_chunks = _remote_rows(sel, identity_cache)
            if remote_chunks is None:
                continue
            for remotes in remote_chunks:
                if len(l7) == 0:
                    # L3-override wildcard: allow-all row for these remotes
                    # (reference: l4.go:209-227 endpointsWithL3Override).
                    rows.append((remotes, PortRuleHTTP()))
                for h in l7.http:
                    rows.append((remotes, h))
        if mesh is not None and rows:
            from ..parallel.rulesharding import mesh_http_model_from_rows

            return mesh_http_model_from_rows(rows, mesh)
        return build_http_model(rows)

    if f.l7_parser == PARSER_TYPE_KAFKA:
        krows: list[tuple[frozenset, PortRuleKafka]] = []
        for sel, l7 in f.l7_rules_per_ep.items():
            remote_chunks = _remote_rows(sel, identity_cache)
            if remote_chunks is None:
                continue
            for remotes in remote_chunks:
                if len(l7) == 0:
                    wildcard = PortRuleKafka()
                    wildcard.sanitize()
                    krows.append((remotes, wildcard))
                for k in l7.kafka:
                    krows.append((remotes, k))
        if mesh is not None and krows:
            from ..parallel.rulesharding import mesh_kafka_model

            return mesh_kafka_model(krows, mesh)
        return build_kafka_model(krows)

    return ConstVerdict(True)  # no L7 restrictions at this layer
