"""Cassandra batch verdict model — device-side (action, table) ACL.

Replaces the per-request rule walk of the reference's cassandra parser
(reference: proxylib/cassandra/cassandraparser.go:58-95 Rule.Matches +
proxylib/proxylib/policymap.go rule cascade) with one device pass over a
batch of pre-tokenized requests.  The CQL tokenizer itself (stateful:
keyspace tracking, prepared-statement cache) stays host-side in the
streaming parser; what scales on device is the ACL:

  allow[f] = OR_r ( remote_ok[f,r] AND
                    (non_query[f] OR (action_ok[f,r] AND table_ok[f,r])) )

- non_query: paths with <= 2 parts (non-query-like opcodes) match every
  rule (cassandraparser.go:74-76)
- action_ok: exact compare against the rule's query_action (or any)
- table_ok: rule regex search over the table name via the shared NFA;
  empty table name skips the table check (cassandraparser.go:87-91)

Input layout [F, MAX_ACTION + MAX_TABLE] uint8: action bytes at offset
0, table bytes at MAX_ACTION — one array, two spans, no gathers.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bytescan import spans_equal_prefix
from ..ops.rxsearch import (
    DeviceDfa,
    DeviceNfa,
    automaton_search_spans,
    compile_automaton,
)
from ..proxylib.parsers.cassandra import CassandraRule
from ..proxylib.policy import CompiledPortRules, PolicyInstance
from .base import ConstVerdict, VerdictModel, pack_remote_sets, remote_ok

MAX_ACTION = 32  # longest action is "create-materialized-view" (24)
MAX_TABLE = 96


@jax.tree_util.register_pytree_node_class
@dataclass
class CassandraBatchModel(VerdictModel):
    nfa: "DeviceDfa | DeviceNfa"  # query_table regex rows
    action_needle: jax.Array  # [R, MAX_ACTION] uint8
    action_len: jax.Array  # [R] int32
    action_any: jax.Array  # [R] bool
    table_none: jax.Array  # [R] bool — rule has no table regex
    remote_ids: jax.Array  # [R, MAX_REMOTES] int32
    any_remote: jax.Array  # [R] bool

    def tree_flatten(self):
        return (
            (self.nfa, self.action_needle, self.action_len, self.action_any,
             self.table_none, self.remote_ids, self.any_remote),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)

    def __call__(self, data, action_len, table_len, non_query, remotes):
        return cassandra_verdicts(
            self, data, action_len, table_len, non_query, remotes
        )


def _collect_rows(rules: CompiledPortRules):
    rows = []  # (remote_set, action_exact, table_regex)
    for rule in rules.rules:
        matchers = rule.l7_matchers or [None]
        for m in matchers:
            if m is None:
                rows.append((rule.allowed_remotes, "", ""))
            else:
                assert isinstance(m, CassandraRule), f"not cassandra: {m!r}"
                rows.append(
                    (rule.allowed_remotes, m.query_action_exact, m.table_regex)
                )
    return rows


def build_cassandra_model(
    policy: PolicyInstance | None, ingress: bool, port: int
) -> ConstVerdict | CassandraBatchModel:
    """Port-cascade build, identical in structure to build_r2d2_model
    (reference port cascade: proxylib/proxylib/policymap.go:208-236)."""
    if policy is None:
        return ConstVerdict(False)
    side = policy.ingress if ingress else policy.egress
    rows = []
    for key in (port, 0):
        rules = side.by_port.get(key)
        if rules is None:
            continue
        if not rules.have_l7_rules or not rules.rules:
            return ConstVerdict(True)
        rows.extend(_collect_rows(rules))
    if not rows:
        return ConstVerdict(False)

    packed_ids, any_remote = pack_remote_sets([r[0] for r in rows])
    n = len(rows)
    action_needle = np.zeros((n, MAX_ACTION), np.uint8)
    action_len = np.zeros((n,), np.int32)
    action_any = np.zeros((n,), bool)
    table_none = np.zeros((n,), bool)
    for i, (_, action, table) in enumerate(rows):
        b = action.encode()
        action_needle[i, : len(b)] = np.frombuffer(b, np.uint8)
        action_len[i] = len(b)
        action_any[i] = len(b) == 0
        table_none[i] = table == ""

    return CassandraBatchModel(
        nfa=compile_automaton([r[2] for r in rows]),
        action_needle=jnp.asarray(action_needle),
        action_len=jnp.asarray(action_len),
        action_any=jnp.asarray(action_any),
        table_none=jnp.asarray(table_none),
        remote_ids=jnp.asarray(packed_ids),
        any_remote=jnp.asarray(any_remote),
    )


def encode_cassandra_batch(requests, f_pad: int | None = None):
    """Host-side batch packing: [(action, table, non_query)] ->
    (data [F, MAX_ACTION+MAX_TABLE], action_len, table_len, non_query,
    overflow).  ``overflow[i]`` marks requests whose tokens exceed the
    fixed widths — callers must fall back to the host oracle for those
    (fail closed, same pattern as the Kafka topic overflow)."""
    n = len(requests)
    f = f_pad or n
    data = np.zeros((f, MAX_ACTION + MAX_TABLE), np.uint8)
    action_len = np.zeros((f,), np.int32)
    table_len = np.zeros((f,), np.int32)
    non_query = np.zeros((f,), bool)
    overflow = np.zeros((n,), bool)
    for i, (action, table, nq) in enumerate(requests):
        ab = action.encode("utf-8", "surrogateescape")
        tb = table.encode("utf-8", "surrogateescape")
        if len(ab) > MAX_ACTION or len(tb) > MAX_TABLE:
            overflow[i] = True
            continue
        data[i, : len(ab)] = np.frombuffer(ab, np.uint8)
        data[i, MAX_ACTION : MAX_ACTION + len(tb)] = np.frombuffer(tb, np.uint8)
        action_len[i] = len(ab)
        table_len[i] = len(tb)
        non_query[i] = nq
    return data, action_len, table_len, non_query, overflow


@jax.jit
def cassandra_verdicts(
    model: CassandraBatchModel,
    data: jax.Array,  # [F, MAX_ACTION + MAX_TABLE] uint8
    action_len: jax.Array,  # [F] int32
    table_len: jax.Array,  # [F] int32
    non_query: jax.Array,  # [F] bool
    remotes: jax.Array,  # [F] int32
) -> jax.Array:
    """allow [F] bool."""
    zeros = jnp.zeros_like(action_len)
    action_ok = (
        spans_equal_prefix(
            data, zeros, action_len, model.action_needle, model.action_len
        )
        | model.action_any[None, :]
    )  # [F, R]
    table_start = jnp.full_like(table_len, MAX_ACTION)
    table_hit = automaton_search_spans(
        model.nfa, data, table_start, table_start + table_len
    )  # [F, R]
    table_ok = (
        model.table_none[None, :] | (table_len == 0)[:, None] | table_hit
    )
    rem = remote_ok(remotes, model.remote_ids, model.any_remote)
    l7_ok = non_query[:, None] | (action_ok & table_ok)
    return jnp.any(rem & l7_ok, axis=1)
