"""Per-protocol batch verdict pipelines — the framework's "model families".

Each model compiles one policy rule set into device arrays and evaluates
whole [flows, bytes] batches at once, replacing the reference's sequential
per-request parse+match:

- ``r2d2``      — toy line protocol (reference: proxylib/r2d2)
- ``http``      — HTTP path/method/host/header rules
                  (reference: envoy/cilium_l7policy.cc, pkg/policy/api/http.go)
- ``kafka``     — Kafka request ACLs (reference: pkg/kafka/policy.go)
- ``cassandra`` — CQL query filtering (reference: proxylib/cassandra)
- ``memcached`` — memcache command/key rules (reference: proxylib/memcached)
- ``dns``       — DNS-over-TCP name policy: exact/wildcard/regex name
                  rules, 0x20-folded, first length-prefixed family
                  (reference: pkg/fqdn + the dnsproxy name walk)

Every model is validated bit-identical against the streaming oracle in
``cilium_tpu.proxylib`` — the same strategy as the reference's op/byte-exact
proxylib test harness.
"""

from .base import ConstVerdict, VerdictModel

__all__ = ["ConstVerdict", "VerdictModel"]
