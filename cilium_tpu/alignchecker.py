"""Struct-layout equivalence check.

reference: pkg/alignchecker/alignchecker.go:48 — the agent refuses to start
if its Go map structs don't byte-match the C structs in bpf/lib/common.h.
Here the authoritative layouts are the documented C sizes; every packed map
struct must serialize to exactly that size so dumps/restores and any future
native consumers stay ABI-compatible.
"""

from __future__ import annotations

# Expected packed sizes from the reference datapath ABI
# (reference: bpf/lib/common.h).
_EXPECTED_SIZES = {
    "policy_key": 8,
    "policy_entry": 24,
    "ipv4_ct_tuple": 14,
    "lb4_key": 8,
    "lb4_service": 12,
    "endpoint_info": 48,
}


class AlignmentError(RuntimeError):
    pass


def check_struct_alignments() -> None:
    """Raise AlignmentError on any layout mismatch; called at daemon boot
    (reference: daemon bootstrap calling alignchecker.CheckStructAlignments)."""
    from .maps.ctmap import TUPLE4_SIZE
    from .maps.lbmap import LB4_KEY_SIZE, LB4_SERVICE_SIZE
    from .maps.lxcmap import ENDPOINT_INFO_SIZE
    from .maps.policymap import ENTRY_SIZE, KEY_SIZE

    actual = {
        "policy_key": KEY_SIZE,
        "policy_entry": ENTRY_SIZE,
        "ipv4_ct_tuple": TUPLE4_SIZE,
        "lb4_key": LB4_KEY_SIZE,
        "lb4_service": LB4_SERVICE_SIZE,
        "endpoint_info": ENDPOINT_INFO_SIZE,
    }
    for name, want in _EXPECTED_SIZES.items():
        got = actual[name]
        if got != want:
            raise AlignmentError(
                f"struct {name}: packed size {got} != expected {want}"
            )
