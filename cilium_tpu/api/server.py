"""Unix-socket HTTP API server and client.

reference: the go-swagger REST API on the agent socket (api/v1/openapi.yaml,
served from daemon/main.go:973+; client pkg/client).  Routes mirror the
reference's /v1 surface: healthz, config, policy (+resolve), endpoint,
identity, ipcache, prefilter, map dumps, metrics.
"""

from __future__ import annotations

import http.client
import json
import re
import socket
import os
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable

from ..labels import LabelArray
from ..utils.unixhttp import serve_unix, shutdown_unix
from ..policy import DPort, rules_from_json
from ..utils.logging import get_logger

log = get_logger("api")


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status


class ApiServer:
    """Routes -> daemon methods (reference: daemon REST handler wiring)."""

    def __init__(self, daemon, path: str) -> None:
        self.daemon = daemon
        self.path = path
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _respond(self, status: int, body: Any) -> None:
                data = (
                    body.encode() if isinstance(body, str)
                    else json.dumps(body).encode()
                )
                self.send_response(status)
                ctype = (
                    "text/plain" if isinstance(body, str)
                    else "application/json"
                )
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            def _dispatch(self, method: str) -> None:
                try:
                    status, body = api.handle(
                        method, self.path, self._body()
                    )
                    self._respond(status, body)
                except ApiError as e:
                    self._respond(e.status, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — surface as 500
                    self._respond(500, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                self._dispatch("GET")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def do_PATCH(self):
                self._dispatch("PATCH")

        self._httpd = serve_unix(path, Handler)

    # -- routing -----------------------------------------------------------

    def handle(self, method: str, path: str, body: bytes) -> tuple[int, Any]:
        path, _, query = path.partition("?")
        params = dict(
            p.split("=", 1) for p in query.split("&") if "=" in p
        )
        d = self.daemon

        if path == "/v1/healthz" and method == "GET":
            return 200, {"cilium": {"state": "Ok"}}
        if path == "/v1/status" and method == "GET":
            return 200, d.status()
        if path == "/metrics" and method == "GET":
            return 200, d.metrics_text()
        if path == "/v1/monitor/recent" and method == "GET":
            return 200, [e.to_dict() for e in d.monitor.recent(200)]
        if path == "/v1/node" and method == "GET":
            # Local node + discovered peers (reference: pkg/node store).
            return 200, {
                "local": d.node_discovery.local.to_dict(),
                "nodes": {
                    name: n.to_dict()
                    for name, n in d.node_discovery.get_nodes().items()
                },
            }
        if path == "/v1/health" and method == "GET":
            from ..health import Prober

            # a fresh Prober's status IS the empty shape — no drift
            prober = d.health_prober if d.health_prober is not None else Prober()
            return 200, prober.get_status()

        if path == "/v1/config":
            if method == "GET":
                cfg = d.config
                return 200, {
                    "cluster_name": cfg.cluster_name,
                    "enable_policy": cfg.enable_policy,
                    "dry_mode": cfg.dry_mode,
                    "batch_flows": cfg.batch_flows,
                    "options": cfg.opts.snapshot(),
                }
            if method == "PATCH":
                changes = json.loads(body.decode() or "{}")
                changed = {}
                for k, v in changes.get("options", {}).items():
                    changed[k] = d.config.opts.set(k, v)
                return 200, {"changed": changed}

        if path == "/v1/policy":
            if method == "GET":
                return 200, json.loads(d.policy_get())
            if method == "PUT":
                rules = rules_from_json(body.decode())
                rev = d.policy_add(rules)
                return 200, {"revision": rev}
            if method == "DELETE":
                lbls = json.loads(body.decode() or "[]")
                rev, deleted = d.policy_delete(LabelArray.parse(*lbls))
                return 200, {"revision": rev, "deleted": deleted}

        if path == "/v1/policy/resolve" and method == "GET":
            dports = []
            if params.get("dport"):
                port, _, proto = params["dport"].partition("/")
                dports = [DPort(int(port), (proto or "ANY").upper())]
            verdict, trace = d.policy_trace(
                LabelArray.parse_select(
                    *params.get("from", "").split(",")
                ) if params.get("from") else LabelArray(),
                LabelArray.parse_select(
                    *params.get("to", "").split(",")
                ) if params.get("to") else LabelArray(),
                dports,
            )
            return 200, {"verdict": verdict, "trace": trace}

        m = re.fullmatch(r"/v1/endpoint(?:/(\d+))?(/regenerate)?", path)
        if m:
            ep_id = int(m.group(1)) if m.group(1) else None
            if method == "GET" and ep_id is None:
                return 200, [
                    _endpoint_model(ep)
                    for ep in d.endpoint_manager.get_endpoints()
                ]
            if method == "GET":
                ep = d.endpoint_manager.lookup(ep_id)
                if ep is None:
                    raise ApiError(404, f"endpoint {ep_id} not found")
                return 200, _endpoint_model(ep, detail=True)
            if method == "PUT" and ep_id is not None:
                spec = json.loads(body.decode() or "{}")
                ep = d.endpoint_create(
                    ep_id,
                    ipv4=spec.get("ipv4", ""),
                    labels=spec.get("labels", []),
                    container_name=spec.get("container_name", ""),
                )
                return 201, _endpoint_model(ep)
            if method == "DELETE" and ep_id is not None:
                if not d.endpoint_delete(ep_id):
                    raise ApiError(404, f"endpoint {ep_id} not found")
                return 200, {}
            if method == "POST" and m.group(2):
                if not d.endpoint_regenerate(ep_id):
                    raise ApiError(404, f"endpoint {ep_id} not found")
                return 200, {}

        m = re.fullmatch(r"/v1/identity(?:/(\d+))?", path)
        if m and method == "GET":
            if m.group(1):
                ident = d.identity_allocator.lookup_by_id(int(m.group(1)))
                if ident is None:
                    raise ApiError(404, "identity not found")
                return 200, {
                    "id": ident.id, "labels": ident.labels.get_model()
                }
            return 200, [
                {"id": i, "labels": lbls.get_model()}
                for i, lbls in sorted(d.get_identity_cache().items())
            ]

        if path == "/v1/ipcache" and method == "GET":
            return 200, [
                {"ip": p.ip, "identity": p.identity}
                for p in d.ipcache.dump()
            ]

        if path == "/v1/prefilter":
            if method == "GET":
                rev, cidrs = d.prefilter.dump()
                return 200, {"revision": rev, "cidrs": cidrs}
            spec = json.loads(body.decode() or "{}")
            if method == "PATCH":
                rev = d.prefilter.insert(
                    spec.get("revision", 0), spec.get("cidrs", [])
                )
                return 200, {"revision": rev}
            if method == "DELETE":
                rev = d.prefilter.delete(
                    spec.get("revision", 0), spec.get("cidrs", [])
                )
                return 200, {"revision": rev}

        m = re.fullmatch(r"/v1/service(?:/(\d+))?", path)
        if m:
            return self._service(method, m.group(1), body)

        m = re.fullmatch(r"/v1/map(?:/([\w-]+))?", path)
        if m and method == "GET":
            return self._map_dump(m.group(1))

        raise ApiError(404, f"no route for {method} {path}")

    def _service(self, method: str, id_str: str | None,
                 body: bytes) -> tuple[int, Any]:
        """Service REST handlers (reference: daemon/loadbalancer.go
        PutServiceID :135 / GetServiceID :289 / DeleteServiceID :183
        + GET /service list)."""
        from ..service import L3n4Addr, ServiceError

        mgr = self.daemon.service_manager
        if method == "GET" and id_str is None:
            return 200, [s.to_model() for s in mgr.list()]
        if id_str is None:
            raise ApiError(400, "service ID required")
        svc_id = int(id_str)
        if svc_id == 0:
            raise ApiError(400, "invalid service ID 0")  # SVCAdd contract
        if method == "GET":
            svc = mgr.get(svc_id)
            if svc is None:
                raise ApiError(404, f"service {svc_id} not found")
            return 200, svc.to_model()
        if method == "DELETE":
            if not mgr.delete_by_id(svc_id):
                raise ApiError(404, f"service {svc_id} not found")
            return 200, {}
        if method == "PUT":
            spec = json.loads(body.decode() or "{}")
            try:
                frontend = L3n4Addr.from_dict(
                    spec.get("frontend-address") or {}
                )
                backends = [
                    L3n4Addr.from_dict(b)
                    for b in spec.get("backend-addresses") or []
                ]
                _, created = mgr.upsert(frontend, backends, id=svc_id)
            except ServiceError as e:
                raise ApiError(460, str(e)) from e
            return (201 if created else 200), mgr.get(svc_id).to_model()
        raise ApiError(405, f"{method} not supported on /v1/service")

    def _map_dump(self, name: str | None) -> tuple[int, Any]:
        """reference: cilium bpf * list / cilium map get."""
        d = self.daemon
        eps = d.endpoint_manager.get_endpoints()
        maps = {
            "ipcache": lambda: [
                {"prefix": k, "identity": v.sec_label}
                for k, v in d.ipcache_map.dump()
            ],
            "ct": lambda: [
                {
                    "daddr": k.daddr, "saddr": k.saddr, "dport": k.dport,
                    "sport": k.sport, "proto": k.nexthdr,
                    "lifetime": e.lifetime, "tx": e.tx_packets,
                    "rx": e.rx_packets,
                }
                for k, e in d.ct_map.dump()
            ],
            "lb": lambda: [
                {"vip": k.address, "dport": k.dport, "slave": k.slave,
                 "target": v.target, "port": v.port, "count": v.count}
                for k, v in d.lb_map.dump()
            ],
            "metrics": lambda: [
                {"direction": dir_, "reason": reason,
                 "count": count, "bytes": nbytes}
                for dir_, reason, count, nbytes in d.metrics_map.dump()
            ],
        }
        for ep in eps:
            maps[f"policy-{ep.id}"] = (
                lambda ep=ep: [
                    {"identity": k.identity, "dport": k.dest_port,
                     "proto": k.proto, "direction": k.direction,
                     "proxy_port": v.proxy_port}
                    for k, v in ep.policy_map.dump()
                ]
            )
        if name is None:
            return 200, sorted(maps)
        if name not in maps:
            raise ApiError(404, f"unknown map {name!r}")
        return 200, maps[name]()

    def close(self) -> None:
        shutdown_unix(self._httpd, self.path)


class _UnixConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float = 10.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._unix_path = path

    def connect(self) -> None:
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(self.timeout)
        self.sock.connect(self._unix_path)


class ApiClient:
    """reference: pkg/client — CLI-side API access."""

    def __init__(self, path: str) -> None:
        self.path = path

    def request(self, method: str, route: str, body: Any = None) -> Any:
        conn = _UnixConnection(self.path)
        try:
            data = None
            headers = {}
            if body is not None:
                data = (
                    body.encode() if isinstance(body, str)
                    else json.dumps(body).encode()
                )
                headers["Content-Type"] = "application/json"
            conn.request(method, route, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read().decode()
            if resp.status >= 400:
                try:
                    msg = json.loads(payload).get("error", payload)
                except ValueError:
                    msg = payload
                raise ApiError(resp.status, msg)
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return json.loads(payload) if payload else None
            return payload
        finally:
            conn.close()

    def get(self, route: str) -> Any:
        return self.request("GET", route)

    def put(self, route: str, body: Any = None) -> Any:
        return self.request("PUT", route, body)

    def post(self, route: str, body: Any = None) -> Any:
        return self.request("POST", route, body)

    def delete(self, route: str, body: Any = None) -> Any:
        return self.request("DELETE", route, body)

    def patch(self, route: str, body: Any = None) -> Any:
        return self.request("PATCH", route, body)


def _endpoint_model(ep, detail: bool = False) -> dict:
    out = {
        "id": ep.id,
        "state": ep.state.value,
        "ipv4": ep.ipv4,
        "identity": ep.security_identity.id if ep.security_identity else 0,
        "labels": ep.labels.get_model(),
        "policy_revision": ep.policy_revision,
    }
    if detail:
        out["ingress_enforced"] = ep.ingress_policy_enabled
        out["egress_enforced"] = ep.egress_policy_enabled
        out["redirects"] = dict(ep.realized_redirects)
        out["policy_map_entries"] = len(ep.policy_map.entries)
        out["spans"] = ep.stats.report()
    return out
