"""REST API over a unix socket (reference: api/v1 + daemon REST handlers
wired at daemon/main.go:990, served on the agent's unix socket)."""

from .server import ApiClient, ApiError, ApiServer

__all__ = ["ApiClient", "ApiError", "ApiServer"]
