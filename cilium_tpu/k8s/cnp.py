"""CiliumNetworkPolicy v2 (CRD) -> api.Rule translation.

reference: pkg/k8s/apis/cilium.io/utils/utils.go ParseToCiliumRule +
pkg/k8s/apis/cilium.io/v2 (the CNP type embeds one ``spec`` or many
``specs`` of api.Rule JSON).  Namespace scoping: the endpointSelector
and every FromEndpoints/ToEndpoints selector are constrained to the
CNP's namespace unless the selector names a namespace itself (or the
rule matches initializing pods, which carry no namespace label);
FromRequires/ToRequires get no k8s prefixing.
"""

from __future__ import annotations

from dataclasses import replace

from ..policy.api import EndpointSelector, PolicyValidationError, Rule
from ..policy.serialize import rule_from_dict
from .network_policy import (
    POD_NAMESPACE_LABEL,
    extract_namespace,
    policy_labels,
)

_POD_PREFIX_KEY = "k8s." + POD_NAMESPACE_LABEL
_ANY_POD_PREFIX_KEY = "any." + POD_NAMESPACE_LABEL
_INIT_KEY = "reserved.init"


def _scope_selector(sel: EndpointSelector, namespace: str, matches_init: bool) -> EndpointSelector:
    """Add the namespace constraint unless the selector already has one,
    names reserved labels, or matches initializing pods
    (reference: utils.go getEndpointSelector)."""
    if sel.has_key_prefix("reserved."):
        return sel
    if matches_init:
        return sel
    if sel.has_key(_POD_PREFIX_KEY) or sel.has_key(_ANY_POD_PREFIX_KEY):
        return sel
    return replace(
        sel,
        match_labels=tuple(
            sorted(sel.match_labels + ((_POD_PREFIX_KEY, namespace),))
        ),
    )


def _namespaces_are_valid(namespace: str, sel: EndpointSelector) -> bool:
    """A user-specified namespace must match the CNP's own namespace
    (reference: utils.go namespacesAreValid)."""
    for key in (_POD_PREFIX_KEY, _ANY_POD_PREFIX_KEY):
        for k, v in sel.match_labels:
            if k == key and v != namespace:
                return False
    return True


def _parse_one(namespace: str, name: str, spec: dict) -> Rule:
    rule = rule_from_dict(spec)
    if rule.endpoint_selector is None:
        raise PolicyValidationError("CNP rule without endpointSelector")
    matches_init = rule.endpoint_selector.has_key(_INIT_KEY)
    if not _namespaces_are_valid(namespace, rule.endpoint_selector):
        raise PolicyValidationError(
            f"CNP rule selects a namespace other than its own ({namespace})"
        )
    rule.endpoint_selector = _scope_selector(
        rule.endpoint_selector, namespace, matches_init
    )
    for ing in rule.ingress:
        ing.from_endpoints = [
            _scope_selector(s, namespace, matches_init)
            for s in ing.from_endpoints
        ]
    for eg in rule.egress:
        eg.to_endpoints = [
            _scope_selector(s, namespace, matches_init)
            for s in eg.to_endpoints
        ]
    rule.labels = policy_labels(namespace, name, "CiliumNetworkPolicy")
    rule.sanitize()
    return rule


def parse_cnp(cnp: dict) -> list[Rule]:
    """CiliumNetworkPolicy dict -> sanitized api.Rules.

    reference: pkg/k8s/apis/cilium.io/v2 CiliumNetworkPolicy.Parse:
    exactly one of ``spec`` / ``specs``.
    """
    meta = cnp.get("metadata") or {}
    namespace = extract_namespace(meta)
    name = meta.get("name", "")
    if not name:
        raise PolicyValidationError("CNP has no name")
    spec = cnp.get("spec")
    specs = cnp.get("specs")
    if spec and specs:
        raise PolicyValidationError("CNP has both spec and specs")
    if not spec and not specs:
        raise PolicyValidationError("CNP has neither spec nor specs")
    out = []
    for s in [spec] if spec else list(specs):
        out.append(_parse_one(namespace, name, s))
    return out
