"""CNI command surface: ADD / DEL / CHECK against the daemon.

reference: plugins/cilium-cni/cilium-cni.go — the CNI plugin the
kubelet execs per pod sandbox.  The full plugin lifecycle is modeled:

- **ADD** (cmdAdd, cilium-cni.go:293): IPAM allocation → veth-pair
  provisioning (connector.SetupVeth records; the kernel steps are
  simulated, see endpoint/connector.py) → peer moved into the sandbox
  netns and renamed eth0 → endpoint create → CNI result with the
  interface records, IP config, and routes (default via the IPAM
  router, mirroring the reference's route list).
- **DEL** (cmdDel, cilium-cni.go:455): idempotent teardown — endpoint
  delete, IP release, interface record removal; a DEL for an unknown
  container or a repeated DEL succeeds silently (kubelet retries DELs).
- **CHECK**: audits that the recorded state is still consistent — the
  endpoint exists with the allocated IP and the interface record is in
  the netns (CNI spec CHECK; the reference predates it, its analog is
  `cilium endpoint get` validation).

Pod labels arrive through the CNI args (the reference resolves them via
the k8s API; tests pass them directly).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..endpoint.connector import VethRecord, move_to_netns, setup_veth
from .ipam import IpamAllocator
from .network_policy import POD_NAMESPACE_LABEL


class CniError(Exception):
    pass


@dataclass
class CniResult:
    """Subset of the CNI result the reference returns (types.Result)."""

    endpoint_id: int
    ip: str
    gateway: str
    routes: list[str] = field(default_factory=list)
    # CNI "interfaces" list: host-side veth + container eth0.
    host_ifname: str = ""
    container_ifname: str = ""
    container_mac: str = ""


@dataclass
class _Container:
    ep_id: int
    ip: str = ""
    veth: VethRecord | None = None


class CniPlugin:
    """ADD/DEL/CHECK dispatcher bound to one daemon + IPAM range."""

    def __init__(self, daemon, ipam: IpamAllocator, mtu: int = 1500) -> None:
        self.daemon = daemon
        self.ipam = ipam
        self.mtu = mtu
        self._lock = threading.Lock()
        self._next_ep_id = 1000
        self._containers: dict[str, _Container] = {}

    def cni_add(
        self,
        container_id: str,
        namespace: str,
        pod_name: str,
        labels: dict[str, str] | None = None,
        netns: str = "",
    ) -> CniResult:
        """reference: cilium-cni.go cmdAdd: IPAM → veth → netns move →
        endpoint create → result."""
        with self._lock:
            if container_id in self._containers:
                raise CniError(f"container {container_id} already added")
            ep_id = self._next_ep_id
            self._next_ep_id += 1
            # Reserve the slot NOW so a concurrent retried ADD for the
            # same container fails the check above instead of double-
            # allocating (kubelet retries ADDs).  The placeholder stays
            # EMPTY (no ip/veth) until the endpoint exists: a DEL racing
            # this in-flight ADD must find nothing to tear down — it
            # must not free the IP the ADD is about to bind.
            self._containers[container_id] = _Container(ep_id)
        try:
            ip = self.ipam.allocate_next(owner=f"{namespace}/{pod_name}")
        except Exception:
            with self._lock:
                self._containers.pop(container_id, None)
            raise
        # Interface provisioning (connector.SetupVeth) + the netns move
        # (cilium-cni.go:342-355).
        veth = setup_veth(
            container_id, netns or f"/var/run/netns/{container_id}",
            mtu=self.mtu,
        )
        move_to_netns(veth)
        veth.routes = [f"0.0.0.0/0 via {self.ipam.router_ip}"]
        lbl_strs = [
            f"k8s:{k}={v}" for k, v in sorted((labels or {}).items())
        ]
        lbl_strs.append(f"k8s:{POD_NAMESPACE_LABEL}={namespace}")
        try:
            self.daemon.endpoint_create(
                ep_id, ipv4=ip, labels=lbl_strs, container_name=container_id
            )
        except Exception:
            self.ipam.release(ip)
            with self._lock:
                self._containers.pop(container_id, None)
            raise
        with self._lock:
            self._containers[container_id] = _Container(ep_id, ip, veth)
        return CniResult(
            endpoint_id=ep_id,
            ip=ip,
            gateway=self.ipam.router_ip,
            routes=list(veth.routes),
            host_ifname=veth.host_ifname,
            container_ifname=veth.container_ifname,
            container_mac=veth.container_mac,
        )

    def cni_del(self, container_id: str) -> bool:
        """reference: cilium-cni.go cmdDel — idempotent (a DEL for an
        unknown container succeeds; kubelet retries DELs).  Returns
        whether state was actually torn down."""
        with self._lock:
            rec = self._containers.pop(container_id, None)
        if rec is None:
            return False
        self.daemon.endpoint_delete(rec.ep_id)
        if rec.ip:
            self.ipam.release(rec.ip)
        return True

    def cni_check(self, container_id: str) -> None:
        """CNI CHECK: raise CniError if the recorded sandbox state has
        drifted from the daemon's."""
        with self._lock:
            rec = self._containers.get(container_id)
        if rec is None:
            raise CniError(f"container {container_id} not configured")
        ep = self.daemon.endpoint_manager.lookup(rec.ep_id)
        if ep is None:
            raise CniError(f"endpoint {rec.ep_id} missing from the daemon")
        if ep.ipv4 != rec.ip:
            raise CniError(
                f"endpoint IP drifted: {ep.ipv4} != allocated {rec.ip}"
            )
        if rec.veth is None or not rec.veth.moved_to_netns:
            raise CniError("container interface never reached the netns")

    def interfaces(self, container_id: str) -> VethRecord | None:
        """The provisioning record for one container (bugtool/tests)."""
        with self._lock:
            rec = self._containers.get(container_id)
        return rec.veth if rec else None

    def interfaces_all(self) -> dict[str, VethRecord]:
        """Snapshot of every container's provisioning record, taken
        under the lock (the bugtool bundle section)."""
        with self._lock:
            return {
                cid: rec.veth
                for cid, rec in self._containers.items()
                if rec.veth is not None
            }
