"""CNI command surface: ADD / DEL against the daemon.

reference: plugins/cilium-cni/cilium-cni.go — the CNI plugin the
kubelet execs per pod sandbox: ADD allocates an IP via the daemon's
IPAM, creates the endpoint (veth plumbing is kernel-side and out of
scope here; the endpoint carries the container/netns identifiers), and
returns the CNI result; DEL releases the IP and deletes the endpoint.

Pod labels arrive through the CNI args (the reference resolves them via
the k8s API; tests pass them directly).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from .ipam import IpamAllocator
from .network_policy import POD_NAMESPACE_LABEL


class CniError(Exception):
    pass


@dataclass
class CniResult:
    """Subset of the CNI result the reference returns (types.Result)."""

    endpoint_id: int
    ip: str
    gateway: str
    routes: list[str] = field(default_factory=list)


class CniPlugin:
    """ADD/DEL dispatcher bound to one daemon + IPAM range."""

    def __init__(self, daemon, ipam: IpamAllocator) -> None:
        self.daemon = daemon
        self.ipam = ipam
        self._lock = threading.Lock()
        self._next_ep_id = 1000
        # container id -> (endpoint id, ip)
        self._containers: dict[str, tuple[int, str]] = {}

    def cni_add(
        self,
        container_id: str,
        namespace: str,
        pod_name: str,
        labels: dict[str, str] | None = None,
    ) -> CniResult:
        """reference: cilium-cni.go cmdAdd: IPAM -> endpoint create."""
        with self._lock:
            if container_id in self._containers:
                raise CniError(f"container {container_id} already added")
            ep_id = self._next_ep_id
            self._next_ep_id += 1
            # Reserve the slot NOW so a concurrent retried ADD for the
            # same container fails the check above instead of double-
            # allocating (kubelet retries ADDs).
            self._containers[container_id] = (ep_id, "")
        try:
            ip = self.ipam.allocate_next(owner=f"{namespace}/{pod_name}")
        except Exception:
            with self._lock:
                self._containers.pop(container_id, None)
            raise
        lbl_strs = [
            f"k8s:{k}={v}" for k, v in sorted((labels or {}).items())
        ]
        lbl_strs.append(f"k8s:{POD_NAMESPACE_LABEL}={namespace}")
        try:
            self.daemon.endpoint_create(
                ep_id, ipv4=ip, labels=lbl_strs, container_name=container_id
            )
        except Exception:
            self.ipam.release(ip)
            with self._lock:
                self._containers.pop(container_id, None)
            raise
        with self._lock:
            self._containers[container_id] = (ep_id, ip)
        return CniResult(
            endpoint_id=ep_id, ip=ip, gateway=self.ipam.router_ip
        )

    def cni_del(self, container_id: str) -> bool:
        """reference: cilium-cni.go cmdDel — idempotent (a DEL for an
        unknown container succeeds; kubelet retries DELs)."""
        with self._lock:
            rec = self._containers.pop(container_id, None)
        if rec is None:
            return False
        ep_id, ip = rec
        self.daemon.endpoint_delete(ep_id)
        if ip:
            self.ipam.release(ip)
        return True
