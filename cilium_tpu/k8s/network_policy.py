"""k8s NetworkPolicy v1 -> api.Rule translation.

reference: pkg/k8s/network_policy.go ParseNetworkPolicy — including the
namespace scoping rules (PodSelector is namespace-local; an empty
NamespaceSelector means "any namespace"), the ipBlock -> CIDRRule
mapping, and the k8s default-deny conversion (a policy with no ingress
rules and ingress policyTypes produces one empty IngressRule).

Policies arrive as plain dicts (parsed JSON/YAML) — there is no k8s
client dependency; the fake apiserver serves the same dict shapes.
"""

from __future__ import annotations

from ..labels import LabelArray, parse_label
from ..policy.api import (
    CIDRRule,
    EndpointSelector,
    IngressRule,
    EgressRule,
    PortProtocol,
    PortRule,
    Rule,
    SelectorRequirement,
)

# reference: pkg/k8s/apis/cilium.io/const.go
POD_NAMESPACE_LABEL = "io.kubernetes.pod.namespace"
POD_NAMESPACE_META_LABELS = "io.cilium.k8s.namespace.labels"
POLICY_LABEL_NAME = "io.cilium.k8s.policy.name"
POLICY_LABEL_NAMESPACE = "io.cilium.k8s.policy.namespace"
POLICY_LABEL_DERIVED_FROM = "io.cilium.k8s.policy.derived-from"

# reference: pkg/annotation (annotation.Name)
ANNOTATION_NAME = "io.cilium.name"

K8S_PREFIX = "k8s:"


def policy_labels(ns: str, name: str, derived_from: str) -> LabelArray:
    """reference: cilium.io/utils GetPolicyLabels."""
    return LabelArray([
        parse_label(f"k8s:{POLICY_LABEL_NAME}={name}"),
        parse_label(f"k8s:{POLICY_LABEL_NAMESPACE}={ns}"),
        parse_label(f"k8s:{POLICY_LABEL_DERIVED_FROM}={derived_from}"),
    ])


def extract_namespace(meta: dict) -> str:
    """reference: pkg/k8s/utils ExtractNamespace (default namespace)."""
    return meta.get("namespace") or "default"


def _k8s_prefix_key(key: str) -> str:
    """Prefix a bare selector key with the k8s source unless it already
    carries a source (reference: NewESFromK8sLabelSelector with
    LabelSourceK8sKeyPrefix; existing source prefixes are kept)."""
    if ":" in key or key.startswith("k8s."):
        return key
    return K8S_PREFIX + key


def selector_from_k8s(sel: dict | None, extra_labels: dict | None = None) -> EndpointSelector:
    """k8s LabelSelector dict -> EndpointSelector with k8s-source keys."""
    sel = sel or {}
    ml = {
        _k8s_prefix_key(k): v for k, v in (sel.get("matchLabels") or {}).items()
    }
    for k, v in (extra_labels or {}).items():
        ml[_k8s_prefix_key(k)] = v
    me = [
        SelectorRequirement(
            key=_k8s_prefix_key(e["key"]),
            operator=e["operator"],
            values=tuple(e.get("values", ())),
        )
        for e in sel.get("matchExpressions") or []
    ]
    return EndpointSelector.from_dict(ml, me)


def _parse_peer(namespace: str, peer: dict) -> EndpointSelector | None:
    """reference: network_policy.go parseNetworkPolicyPeer."""
    ns_sel = peer.get("namespaceSelector")
    pod_sel = peer.get("podSelector")
    if ns_sel is not None:
        ml = {
            f"{POD_NAMESPACE_META_LABELS}.{k}": v
            for k, v in (ns_sel.get("matchLabels") or {}).items()
        }
        me = [
            SelectorRequirement(
                key=_k8s_prefix_key(f"{POD_NAMESPACE_META_LABELS}.{e['key']}"),
                operator=e["operator"],
                values=tuple(e.get("values", ())),
            )
            for e in ns_sel.get("matchExpressions") or []
        ]
        if not ml and not me:
            # Empty namespace selector selects ALL namespaces (the
            # namespace label merely exists).
            me = [
                SelectorRequirement(
                    key=_k8s_prefix_key(POD_NAMESPACE_LABEL),
                    operator="Exists",
                )
            ]
        combined = dict((_k8s_prefix_key(k), v) for k, v in ml.items())
        # Pod selector terms AND with the namespace terms.
        for k, v in ((pod_sel or {}).get("matchLabels") or {}).items():
            combined[_k8s_prefix_key(k)] = v
        me += [
            SelectorRequirement(
                key=_k8s_prefix_key(e["key"]),
                operator=e["operator"],
                values=tuple(e.get("values", ())),
            )
            for e in (pod_sel or {}).get("matchExpressions") or []
        ]
        return EndpointSelector.from_dict(combined, me)
    if pod_sel is not None:
        # Namespace-local pod selector.
        return selector_from_k8s(
            pod_sel, extra_labels={POD_NAMESPACE_LABEL: namespace}
        )
    return None


def _ip_block_to_cidr_rule(block: dict) -> CIDRRule:
    return CIDRRule(
        cidr=block["cidr"],
        except_cidrs=list(block.get("except", ())),
    )


def np_policy_name(np: dict) -> str:
    """The policy name used for derived labels: the io.cilium.name
    annotation wins over metadata.name (reference: GetPolicyLabelsv1)."""
    meta = np.get("metadata") or {}
    return (meta.get("annotations") or {}).get(ANNOTATION_NAME) or meta.get(
        "name", ""
    )


def _parse_ports(ports: list[dict]) -> list[PortRule]:
    """reference: network_policy.go parsePorts.  Protocol-only and named
    ports translate to an empty/non-numeric Port string, which
    Rule.Sanitize rejects — EXACTLY as the reference does (its
    PortProtocol.sanitize ParseUints the string), so such policies fail
    import in both implementations."""
    out = []
    for p in ports:
        if p.get("protocol") is None and p.get("port") is None:
            continue
        proto = (p.get("protocol") or "TCP").upper()
        port = str(p.get("port") or "")
        out.append(
            PortRule(ports=[PortProtocol(port=port, protocol=proto)])
        )
    return out


def _wildcard_selector() -> EndpointSelector:
    """reserved:all — matches every source (reference: NewESFromLabels
    with the reserved all label)."""
    return EndpointSelector.from_dict({"reserved:all": ""})


def parse_network_policy(np: dict) -> list[Rule]:
    """k8s NetworkPolicy v1 (dict form) -> sanitized api.Rules.

    reference: pkg/k8s/network_policy.go:123 ParseNetworkPolicy.
    """
    meta = np.get("metadata") or {}
    spec = np.get("spec") or {}
    namespace = extract_namespace(meta)
    name = np_policy_name(np)
    policy_types = spec.get("policyTypes") or []

    ingresses: list[IngressRule] = []
    for i_rule in spec.get("ingress") or []:
        ing = IngressRule()
        if i_rule.get("ports"):
            ing.to_ports = _parse_ports(i_rule["ports"])
        froms = i_rule.get("from") or []
        if froms:
            for peer in froms:
                sel = _parse_peer(namespace, peer)
                if sel is not None:
                    ing.from_endpoints.append(sel)
                if peer.get("ipBlock"):
                    ing.from_cidr_set.append(
                        _ip_block_to_cidr_rule(peer["ipBlock"])
                    )
        else:
            # Empty/missing From matches all sources.
            ing.from_endpoints.append(_wildcard_selector())
        ingresses.append(ing)

    egresses: list[EgressRule] = []
    for e_rule in spec.get("egress") or []:
        eg = EgressRule()
        tos = e_rule.get("to") or []
        if tos:
            for peer in tos:
                if peer.get("namespaceSelector") is not None or peer.get(
                    "podSelector"
                ) is not None:
                    sel = _parse_peer(namespace, peer)
                    if sel is not None:
                        eg.to_endpoints.append(sel)
                if peer.get("ipBlock"):
                    eg.to_cidr_set.append(
                        _ip_block_to_cidr_rule(peer["ipBlock"])
                    )
        else:
            eg.to_endpoints.append(_wildcard_selector())
        if e_rule.get("ports"):
            eg.to_ports = _parse_ports(e_rule["ports"])
        egresses.append(eg)

    # k8s default-deny -> cilium default-deny: no ingress rules + an
    # ingress policyType (or no explicit egress type) yields one empty
    # (deny-by-selection) ingress rule.
    if not ingresses and (
        "Ingress" in policy_types or "Egress" not in policy_types
    ):
        ingresses = [IngressRule()]
    if not egresses and "Egress" in policy_types:
        egresses = [EgressRule()]

    rule = Rule(
        endpoint_selector=selector_from_k8s(
            spec.get("podSelector"),
            extra_labels={POD_NAMESPACE_LABEL: namespace},
        ),
        ingress=ingresses,
        egress=egresses,
        labels=policy_labels(namespace, name, "NetworkPolicy"),
    )
    rule.sanitize()
    return [rule]
