"""Kubernetes integration: NetworkPolicy/CNP translation, watch loop,
IPAM, and the CNI command surface.

reference: pkg/k8s (translation), daemon/k8s_watcher.go (informers ->
PolicyAdd/Delete), pkg/ipam + plugins/cilium-cni (pod plumbing).  The
apiserver client is replaced by a fake in-process apiserver fixture
(k8s/apiserver.py) with the same list+watch contract, so the watcher
logic is identical whether events come from a test or a real stream.
"""

from .apiserver import FakeApiServer, WatchEvent
from .cni import CniPlugin
from .cnp import parse_cnp
from .ipam import IpamAllocator
from .network_policy import parse_network_policy
from .rule_translate import translate_to_services
from .watcher import K8sWatcher

__all__ = [
    "CniPlugin",
    "FakeApiServer",
    "IpamAllocator",
    "K8sWatcher",
    "WatchEvent",
    "parse_cnp",
    "parse_network_policy",
    "translate_to_services",
]
