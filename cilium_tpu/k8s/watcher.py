"""k8s watch loop: apiserver events -> daemon policy add/delete.

reference: daemon/k8s_watcher.go — NetworkPolicy v1 handlers (:472
addK8sNetworkPolicyV1/update/delete), CiliumNetworkPolicy handlers
(:1703 addCiliumNetworkPolicyV2, :1750 delete, CNP status updates
:1690-1946), and Endpoints handlers driving ToServices translation.

Updates are delete-by-labels + re-add (the reference's update path for
both kinds), keyed on the derived policy labels so user rules and other
policies are untouched.  CNP status (ok/error per node) writes back to
the fake apiserver the way the reference PATCHes the CRD status.
"""

from __future__ import annotations

import logging
import threading

from ..labels import LabelArray
from ..service import L3n4Addr, ServiceError
from . import apiserver as api
from .cnp import parse_cnp
from .network_policy import np_policy_name, parse_network_policy, policy_labels
from .rule_translate import translate_to_services

log = logging.getLogger(__name__)


class K8sWatcher:
    """Consumes a FakeApiServer watch stream and drives the daemon."""

    def __init__(self, daemon, apisrv: api.FakeApiServer,
                 node_name: str = "node-0") -> None:
        self.daemon = daemon
        self.apiserver = apisrv
        self.node_name = node_name
        self._queue = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.events_seen = 0
        # Last known endpoints per (namespace, name) service for the
        # ToServices revert pass on endpoint updates.
        self._svc_backends: dict[tuple, list[str]] = {}
        # Service/Endpoints stores driving the load balancer
        # (reference: d.loadBalancer.K8sServices / K8sEndpoints,
        # daemon/k8s_watcher.go:822,945).
        self._k8s_services: dict[tuple, dict] = {}
        self._k8s_eps: dict[tuple, dict] = {}
        # Frontends currently programmed per service, for teardown of
        # ports that disappear (reference: delK8sSVCs).
        self._lb_frontends: dict[tuple, list[L3n4Addr]] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "K8sWatcher":
        self._queue = self.apiserver.watch()
        self._thread = threading.Thread(
            target=self._loop, name="k8s-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._queue is not None:
            self._queue.put(None)  # wake
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            ev = self._queue.get()
            if ev is None:
                return
            try:
                self.handle(ev)
            except Exception:  # noqa: BLE001 — one bad object must not
                log.exception("k8s event failed: %s", ev)  # kill the loop
            self.events_seen += 1

    def sync(self, timeout: float = 5.0) -> None:
        """Wait until every queued event has been handled (test helper —
        the informer 'cache synced' analog)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:  # type: ignore[attr-defined]
                return
            time.sleep(0.005)
        raise TimeoutError("k8s watcher did not drain in time")

    # -- event handling ---------------------------------------------------

    def handle(self, ev: api.WatchEvent) -> None:
        try:
            if ev.kind == api.KIND_NETWORK_POLICY:
                self._handle_np(ev)
            elif ev.kind == api.KIND_CNP:
                self._handle_cnp(ev)
            elif ev.kind == api.KIND_ENDPOINTS:
                self._handle_endpoints(ev)
            elif ev.kind == api.KIND_SERVICE:
                self._handle_service(ev)
        finally:
            if self._queue is not None:
                try:
                    self._queue.task_done()
                except ValueError:
                    pass

    def _delete_by_labels(self, lbls: LabelArray) -> int:
        _, deleted = self.daemon.policy_delete(lbls)
        return deleted

    def _handle_np(self, ev: api.WatchEvent) -> None:
        """reference: k8s_watcher.go addK8sNetworkPolicyV1 /
        updateK8sNetworkPolicyV1 / deleteK8sNetworkPolicyV1."""
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        # The label-derived name honors the io.cilium.name annotation
        # (must match parse_network_policy or deletes would miss).
        name = np_policy_name(ev.obj)
        lbls = policy_labels(ns, name, "NetworkPolicy")
        if ev.type == api.DELETED:
            self._delete_by_labels(lbls)
            return
        rules = parse_network_policy(ev.obj)
        if ev.type == api.MODIFIED:
            self._delete_by_labels(lbls)
        self.daemon.policy_add(rules)

    def _handle_cnp(self, ev: api.WatchEvent) -> None:
        """reference: k8s_watcher.go:1703 addCiliumNetworkPolicyV2 (+
        CNP node-status update on success/failure)."""
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        name = meta.get("name", "")
        lbls = policy_labels(ns, name, "CiliumNetworkPolicy")
        if ev.type == api.DELETED:
            self._delete_by_labels(lbls)
            return
        try:
            rules = parse_cnp(ev.obj)
            if ev.type == api.MODIFIED:
                self._delete_by_labels(lbls)
            self.daemon.policy_add(rules)
            self._set_cnp_status(ev.obj, ok=True, error="")
        except Exception as exc:  # noqa: BLE001 — status carries the error
            self._set_cnp_status(ev.obj, ok=False, error=str(exc))
            raise

    def _set_cnp_status(self, cnp: dict, ok: bool, error: str) -> None:
        """Write the per-node status back (reference:
        updateCiliumNetworkPolicyV2AnnotationsOnly / CNPStatus nodes)."""
        status = cnp.setdefault("status", {}).setdefault("nodes", {})
        status[self.node_name] = {"ok": ok, "error": error}

    def _handle_endpoints(self, ev: api.WatchEvent) -> None:
        """Endpoints changes re-translate ToServices rules
        (reference: k8s_watcher.go addK8sEndpointV1 ->
        d.policy.TranslateRules)."""
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        name = meta.get("name", "")
        parsed_eps = _parse_endpoints(ev.obj)
        ips = parsed_eps["ips"]
        svc = self.apiserver.get(api.KIND_SERVICE, ns, name) or {}
        svc_labels = (svc.get("metadata") or {}).get("labels") or {}
        repo = self.daemon.get_policy_repository()
        key = (ns, name)
        old = self._svc_backends.get(key, [])
        with repo.mutex:
            rules = list(repo.rules)
            if old and ev.type in (api.MODIFIED, api.DELETED):
                translate_to_services(
                    rules, name, ns, old, svc_labels, revert=True
                )
            if ev.type != api.DELETED:
                res = translate_to_services(
                    rules, name, ns, ips, svc_labels, revert=False
                )
            else:
                res = None
        if ev.type == api.DELETED:
            self._svc_backends.pop(key, None)
        else:
            self._svc_backends[key] = ips
        if res is None or res.added_cidrs or res.removed_cidrs or old:
            self.daemon.trigger_policy_updates()

        # Feed the load-balancer sync (reference: addK8sEndpointV1 ->
        # addK8sSVCs with the stored service, k8s_watcher.go:945-1032).
        if ev.type == api.DELETED:
            self._k8s_eps.pop(key, None)
        else:
            self._k8s_eps[key] = parsed_eps
        self._sync_lb(key)

    # -- services -> load balancer ----------------------------------------

    def _handle_service(self, ev: api.WatchEvent) -> None:
        """reference: daemon/k8s_watcher.go:822 addK8sServiceV1 /
        :858 update / :862 delete — stores the parsed service and
        reconciles the LB maps against it."""
        meta = ev.obj.get("metadata") or {}
        key = (meta.get("namespace") or "default", meta.get("name", ""))
        if ev.type == api.DELETED:
            self._k8s_services.pop(key, None)
        else:
            self._k8s_services[key] = _parse_service(ev.obj)
        self._sync_lb(key)

    def _sync_lb(self, key: tuple) -> None:
        """Reconcile the programmed frontends for one (ns, name)
        against the current Service + Endpoints pair (reference:
        addK8sSVCs/delK8sSVCs, k8s_watcher.go:1137,1196).  Headless
        services (no clusterIP) program nothing."""
        mgr = self.daemon.service_manager
        svc = self._k8s_services.get(key)
        eps = self._k8s_eps.get(key) or {"ips": [], "ports": {}}

        desired: list[tuple[L3n4Addr, list[L3n4Addr]]] = []
        if svc is not None and svc["frontend_ip"]:
            seen_ports = set()
            for p in svc["ports"]:
                if p["port"] in seen_ports:  # reference: getUniqPorts
                    continue
                seen_ports.add(p["port"])
                fe = L3n4Addr(
                    svc["frontend_ip"], p["port"], p.get("protocol", "TCP")
                )
                be_port = eps["ports"].get(p["name"])
                backends = []
                if be_port is not None:
                    backends = [
                        L3n4Addr(ip, be_port[0], be_port[1])
                        for ip in eps["ips"]
                    ]
                desired.append((fe, backends))

        previous = {fe.key(): fe for fe in self._lb_frontends.get(key, [])}
        desired_keys = {fe.key() for fe, _ in desired}
        for fe_key, fe in previous.items():
            if fe_key not in desired_keys:
                mgr.delete_by_frontend(fe)
        programmed = []
        for fe, backends in desired:
            try:
                mgr.upsert(fe, backends)
                programmed.append(fe)
            except ServiceError:
                log.exception("k8s service %s: LB programming failed", key)
                # A frontend programmed by an earlier sync stays tracked
                # even if this update failed — otherwise its map entries
                # would leak past the Service's deletion.
                if fe.key() in previous:
                    programmed.append(previous[fe.key()])
        if programmed:
            self._lb_frontends[key] = programmed
        else:
            self._lb_frontends.pop(key, None)


def _parse_service(obj: dict) -> dict:
    """Parse a k8s Service into the LB-relevant fields (reference:
    loadbalancer.K8sServiceInfo; 'None'/'' clusterIP = headless,
    k8s_watcher.go:826 NewK8sServiceInfo IsHeadless)."""
    spec = obj.get("spec") or {}
    cluster_ip = spec.get("clusterIP") or ""
    if cluster_ip in ("None", "none"):
        cluster_ip = ""
    ports = [
        {
            "name": p.get("name", ""),
            "port": int(p["port"]),
            "protocol": (p.get("protocol") or "TCP").upper(),
        }
        for p in spec.get("ports") or []
        if p.get("port")
    ]
    return {"frontend_ip": cluster_ip, "ports": ports}


def _parse_endpoints(obj: dict) -> dict:
    """Parse k8s Endpoints into backend IPs + per-name ports
    (reference: loadbalancer.K8sServiceEndpoint: BEIPs set + Ports
    map keyed by port name)."""
    ips: list[str] = []
    ports: dict[str, tuple[int, str]] = {}
    for subset in obj.get("subsets") or []:
        for a in subset.get("addresses") or []:
            if a.get("ip") and a["ip"] not in ips:
                ips.append(a["ip"])
        for p in subset.get("ports") or []:
            if p.get("port"):
                ports[p.get("name", "")] = (
                    int(p["port"]), (p.get("protocol") or "TCP").upper()
                )
    return {"ips": ips, "ports": ports}
