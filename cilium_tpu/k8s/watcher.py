"""k8s watch loop: apiserver events -> daemon policy add/delete.

reference: daemon/k8s_watcher.go — NetworkPolicy v1 handlers (:472
addK8sNetworkPolicyV1/update/delete), CiliumNetworkPolicy handlers
(:1703 addCiliumNetworkPolicyV2, :1750 delete, CNP status updates
:1690-1946), and Endpoints handlers driving ToServices translation.

Updates are delete-by-labels + re-add (the reference's update path for
both kinds), keyed on the derived policy labels so user rules and other
policies are untouched.  CNP status (ok/error per node) writes back to
the fake apiserver the way the reference PATCHes the CRD status.
"""

from __future__ import annotations

import logging
import threading

from ..labels import LabelArray
from . import apiserver as api
from .cnp import parse_cnp
from .network_policy import np_policy_name, parse_network_policy, policy_labels
from .rule_translate import translate_to_services

log = logging.getLogger(__name__)


class K8sWatcher:
    """Consumes a FakeApiServer watch stream and drives the daemon."""

    def __init__(self, daemon, apisrv: api.FakeApiServer,
                 node_name: str = "node-0") -> None:
        self.daemon = daemon
        self.apiserver = apisrv
        self.node_name = node_name
        self._queue = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.events_seen = 0
        # Last known endpoints per (namespace, name) service for the
        # ToServices revert pass on endpoint updates.
        self._svc_backends: dict[tuple, list[str]] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "K8sWatcher":
        self._queue = self.apiserver.watch()
        self._thread = threading.Thread(
            target=self._loop, name="k8s-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._queue is not None:
            self._queue.put(None)  # wake
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            ev = self._queue.get()
            if ev is None:
                return
            try:
                self.handle(ev)
            except Exception:  # noqa: BLE001 — one bad object must not
                log.exception("k8s event failed: %s", ev)  # kill the loop
            self.events_seen += 1

    def sync(self, timeout: float = 5.0) -> None:
        """Wait until every queued event has been handled (test helper —
        the informer 'cache synced' analog)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:  # type: ignore[attr-defined]
                return
            time.sleep(0.005)
        raise TimeoutError("k8s watcher did not drain in time")

    # -- event handling ---------------------------------------------------

    def handle(self, ev: api.WatchEvent) -> None:
        try:
            if ev.kind == api.KIND_NETWORK_POLICY:
                self._handle_np(ev)
            elif ev.kind == api.KIND_CNP:
                self._handle_cnp(ev)
            elif ev.kind == api.KIND_ENDPOINTS:
                self._handle_endpoints(ev)
            # Services are consumed via Endpoints; Service objects carry
            # metadata only for ToServices label matching.
        finally:
            if self._queue is not None:
                try:
                    self._queue.task_done()
                except ValueError:
                    pass

    def _delete_by_labels(self, lbls: LabelArray) -> int:
        _, deleted = self.daemon.policy_delete(lbls)
        return deleted

    def _handle_np(self, ev: api.WatchEvent) -> None:
        """reference: k8s_watcher.go addK8sNetworkPolicyV1 /
        updateK8sNetworkPolicyV1 / deleteK8sNetworkPolicyV1."""
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        # The label-derived name honors the io.cilium.name annotation
        # (must match parse_network_policy or deletes would miss).
        name = np_policy_name(ev.obj)
        lbls = policy_labels(ns, name, "NetworkPolicy")
        if ev.type == api.DELETED:
            self._delete_by_labels(lbls)
            return
        rules = parse_network_policy(ev.obj)
        if ev.type == api.MODIFIED:
            self._delete_by_labels(lbls)
        self.daemon.policy_add(rules)

    def _handle_cnp(self, ev: api.WatchEvent) -> None:
        """reference: k8s_watcher.go:1703 addCiliumNetworkPolicyV2 (+
        CNP node-status update on success/failure)."""
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        name = meta.get("name", "")
        lbls = policy_labels(ns, name, "CiliumNetworkPolicy")
        if ev.type == api.DELETED:
            self._delete_by_labels(lbls)
            return
        try:
            rules = parse_cnp(ev.obj)
            if ev.type == api.MODIFIED:
                self._delete_by_labels(lbls)
            self.daemon.policy_add(rules)
            self._set_cnp_status(ev.obj, ok=True, error="")
        except Exception as exc:  # noqa: BLE001 — status carries the error
            self._set_cnp_status(ev.obj, ok=False, error=str(exc))
            raise

    def _set_cnp_status(self, cnp: dict, ok: bool, error: str) -> None:
        """Write the per-node status back (reference:
        updateCiliumNetworkPolicyV2AnnotationsOnly / CNPStatus nodes)."""
        status = cnp.setdefault("status", {}).setdefault("nodes", {})
        status[self.node_name] = {"ok": ok, "error": error}

    def _handle_endpoints(self, ev: api.WatchEvent) -> None:
        """Endpoints changes re-translate ToServices rules
        (reference: k8s_watcher.go addK8sEndpointV1 ->
        d.policy.TranslateRules)."""
        meta = ev.obj.get("metadata") or {}
        ns = meta.get("namespace") or "default"
        name = meta.get("name", "")
        ips = [
            a.get("ip")
            for subset in ev.obj.get("subsets") or []
            for a in subset.get("addresses") or []
            if a.get("ip")
        ]
        svc = self.apiserver.get(api.KIND_SERVICE, ns, name) or {}
        svc_labels = (svc.get("metadata") or {}).get("labels") or {}
        repo = self.daemon.get_policy_repository()
        key = (ns, name)
        old = self._svc_backends.get(key, [])
        with repo.mutex:
            rules = list(repo.rules)
            if old and ev.type in (api.MODIFIED, api.DELETED):
                translate_to_services(
                    rules, name, ns, old, svc_labels, revert=True
                )
            if ev.type != api.DELETED:
                res = translate_to_services(
                    rules, name, ns, ips, svc_labels, revert=False
                )
            else:
                res = None
        if ev.type == api.DELETED:
            self._svc_backends.pop(key, None)
        else:
            self._svc_backends[key] = ips
        if res is None or res.added_cidrs or res.removed_cidrs or old:
            self.daemon.trigger_policy_updates()
