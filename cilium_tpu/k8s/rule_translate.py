"""ToServices -> ToCIDRSet translation driven by k8s service endpoints.

reference: pkg/k8s/rule_translate.go RuleTranslator — when a service's
endpoints change, every egress rule whose ``toServices`` names (or
label-selects) the service gets GENERATED single-address ToCIDRSet
entries for the backend IPs; a revert pass removes the generated
entries for backends that disappeared.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field

from ..policy.api import CIDRRule, EgressRule, Rule, Service


@dataclass
class TranslationResult:
    num_to_services_rules: int = 0
    added_cidrs: list[str] = field(default_factory=list)
    removed_cidrs: list[str] = field(default_factory=list)


def _service_matches(
    svc: Service, name: str, namespace: str, service_labels: dict
) -> bool:
    """reference: rule_translate.go serviceMatches."""
    if svc.k8s_service_selector is not None:
        from ..labels import LabelArray, parse_label

        lbls = LabelArray(
            parse_label(f"{k}={v}") for k, v in (service_labels or {}).items()
        )
        return svc.k8s_service_selector.matches(lbls) and (
            svc.k8s_service_namespace in ("", namespace)
        )
    if svc.k8s_service_name:
        return svc.k8s_service_name == name and (
            svc.k8s_service_namespace in ("", namespace)
        )
    return False


def _host_cidr(ip: str) -> str:
    addr = ipaddress.ip_address(ip)
    return f"{addr}/{32 if addr.version == 4 else 128}"


def _populate(egress: EgressRule, backend_ips: list[str], result: TranslationResult) -> None:
    """reference: rule_translate.go generateToCidrFromEndpoint."""
    for ip in backend_ips:
        addr = ipaddress.ip_address(ip)
        covered = any(
            addr in ipaddress.ip_network(c.cidr, strict=False)
            for c in egress.to_cidr_set
        )
        if not covered:
            cidr = _host_cidr(ip)
            egress.to_cidr_set.append(CIDRRule(cidr=cidr, generated=True))
            result.added_cidrs.append(cidr)


def _depopulate(egress: EgressRule, backend_ips: list[str], result: TranslationResult) -> None:
    """Remove GENERATED entries matching the endpoint's backends
    (reference: rule_translate.go deleteToCidrFromEndpoint)."""
    targets = {str(ipaddress.ip_network(_host_cidr(ip))) for ip in backend_ips}
    kept = []
    for c in egress.to_cidr_set:
        key = str(ipaddress.ip_network(c.cidr, strict=False))
        if c.generated and key in targets:
            result.removed_cidrs.append(c.cidr)
        else:
            kept.append(c)
    egress.to_cidr_set = kept


def translate_to_services(
    rules: list[Rule],
    service_name: str,
    service_namespace: str,
    backend_ips: list[str],
    service_labels: dict | None = None,
    revert: bool = False,
) -> TranslationResult:
    """Populate (or revert) generated ToCIDRSet entries on every egress
    rule whose toServices matches the service.  Mirrors the reference's
    Translate over all rules' egress sections; the caller bumps the
    policy revision / triggers regeneration afterwards
    (reference: pkg/policy/repository.go:674 TranslateRules)."""
    result = TranslationResult()
    for rule in rules:
        for egress in rule.egress:
            for svc in egress.to_services:
                result.num_to_services_rules += 1
                if _service_matches(
                    svc, service_name, service_namespace, service_labels or {}
                ):
                    _depopulate(egress, backend_ips, result)
                    if not revert:
                        _populate(egress, backend_ips, result)
    return result
