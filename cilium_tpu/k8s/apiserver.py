"""Fake in-process apiserver: object store with list+watch semantics.

The test/development stand-in for the k8s apiserver the reference's
informers talk to (reference: daemon/k8s_watcher.go EnableK8sWatcher
cache.NewListWatchFromClient).  Same contract the watcher needs:
``list`` returns the current objects of a kind, ``watch`` returns a
subscription that replays ADDED events for existing objects and then
streams subsequent ADDED/MODIFIED/DELETED events in order.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

# Object kinds the watcher consumes (reference: k8s_watcher.go:472-703).
KIND_NETWORK_POLICY = "NetworkPolicy"
KIND_CNP = "CiliumNetworkPolicy"
KIND_SERVICE = "Service"
KIND_ENDPOINTS = "Endpoints"


@dataclass
class WatchEvent:
    type: str  # ADDED / MODIFIED / DELETED
    kind: str
    obj: dict


def obj_key(obj: dict) -> tuple[str, str]:
    meta = obj.get("metadata") or {}
    return (meta.get("namespace") or "default", meta.get("name", ""))


class FakeApiServer:
    """Thread-safe object store + watch fan-out."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._objects: dict[str, dict[tuple, dict]] = {}
        self._watchers: list[queue.Queue] = []
        self._resource_version = 0

    def list(self, kind: str) -> list[dict]:
        with self._lock:
            return list(self._objects.get(kind, {}).values())

    def watch(self) -> "queue.Queue[WatchEvent]":
        """Subscribe; existing objects replay as ADDED first (informer
        initial-sync semantics)."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            for kind, objs in self._objects.items():
                for obj in objs.values():
                    q.put(WatchEvent(ADDED, kind, obj))
            self._watchers.append(q)
        return q

    def _publish(self, ev: WatchEvent) -> None:
        for q in self._watchers:
            q.put(ev)

    def upsert(self, kind: str, obj: dict) -> None:
        key = obj_key(obj)
        with self._lock:
            objs = self._objects.setdefault(kind, {})
            ev_type = MODIFIED if key in objs else ADDED
            self._resource_version += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(
                self._resource_version
            )
            objs[key] = obj
            self._publish(WatchEvent(ev_type, kind, obj))

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        with self._lock:
            objs = self._objects.get(kind, {})
            obj = objs.pop((namespace or "default", name), None)
            if obj is None:
                return False
            self._resource_version += 1
            self._publish(WatchEvent(DELETED, kind, obj))
            return True

    def get(self, kind: str, namespace: str, name: str) -> dict | None:
        with self._lock:
            return self._objects.get(kind, {}).get(
                (namespace or "default", name)
            )
