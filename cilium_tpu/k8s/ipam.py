"""Host-scope IPAM: per-node pod CIDR allocator.

reference: pkg/ipam (host-scope allocator from the node's allocation
CIDR) + daemon/ipam.go REST handlers.  Sequential-with-free-list
allocation over the usable host range; the network/broadcast addresses
and the router IP (first usable) are reserved.
"""

from __future__ import annotations

import ipaddress
import threading


class IpamError(Exception):
    pass


class IpamAllocator:
    """reference: pkg/ipam/allocator.go."""

    def __init__(self, cidr: str) -> None:
        self.network = ipaddress.ip_network(cidr, strict=False)
        self._lock = threading.Lock()
        self._allocated: dict[str, str] = {}  # ip -> owner
        first = int(self.network.network_address) + 1
        self.router_ip = str(ipaddress.ip_address(first))
        self._allocated[self.router_ip] = "router"
        self._next = first + 1
        self._free: list[int] = []
        self._last = int(self.network.broadcast_address) - (
            1 if self.network.version == 4 else 0
        )

    def allocate_next(self, owner: str) -> str:
        """Next free address (reference: allocator.go AllocateNext)."""
        with self._lock:
            if self._free:
                ip = ipaddress.ip_address(self._free.pop())
            else:
                # Skip addresses claimed out-of-band via allocate_ip.
                while (
                    self._next <= self._last
                    and str(ipaddress.ip_address(self._next)) in self._allocated
                ):
                    self._next += 1
                if self._next > self._last:
                    raise IpamError(f"range {self.network} exhausted")
                ip = ipaddress.ip_address(self._next)
                self._next += 1
            s = str(ip)
            self._allocated[s] = owner
            return s

    def allocate_ip(self, ip: str, owner: str) -> str:
        """Allocate a specific address (reference: allocator.go Allocate)."""
        with self._lock:
            addr = ipaddress.ip_address(ip)
            if addr not in self.network:
                raise IpamError(f"{ip} not in range {self.network}")
            if ip in self._allocated:
                raise IpamError(f"{ip} already allocated")
            # A previously released address must leave the free list or
            # allocate_next would hand it out a second time.
            try:
                self._free.remove(int(addr))
            except ValueError:
                pass
            self._allocated[ip] = owner
            return ip

    def release(self, ip: str) -> bool:
        with self._lock:
            if self._allocated.pop(ip, None) is None:
                return False
            self._free.append(int(ipaddress.ip_address(ip)))
            return True

    def dump(self) -> dict[str, str]:
        with self._lock:
            return dict(self._allocated)

    def __len__(self) -> int:
        with self._lock:
            return len(self._allocated)
