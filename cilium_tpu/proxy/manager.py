"""Proxy port allocation and redirect lifecycle.

reference: pkg/proxy/proxy.go — port allocator over the 10000-20000 range
(daemon/daemon.go:1327), CreateOrUpdateRedirect/RemoveRedirect keyed by
ProxyID (pkg/policy/proxyid.go), dispatch by L7 parser type
(proxy.go:229-236).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..policy.l4 import L4Filter
from ..utils import defaults
from ..utils.logging import get_logger

log = get_logger("proxy")


@dataclass
class Redirect:
    """reference: pkg/proxy Redirect."""

    proxy_id: str
    proxy_port: int
    endpoint_id: int
    ingress: bool
    l7_parser: str
    l4_filter: Optional[L4Filter] = None
    # Backend handle: the runtime batch engine serving this redirect.
    implementation: object = None


class ProxyManager:
    """reference: pkg/proxy/proxy.go:59 Proxy."""

    def __init__(
        self,
        port_min: int = defaults.PROXY_PORT_MIN,
        port_max: int = defaults.PROXY_PORT_MAX,
        create_backend: Callable[[Redirect], object] | None = None,
    ) -> None:
        self.port_min = port_min
        self.port_max = port_max
        self.redirects: dict[str, Redirect] = {}
        self.allocated_ports: set[int] = set()
        self._next = port_min
        self._mutex = threading.RLock()
        # Called on new redirects to instantiate the serving engine; the
        # daemon wires this to the runtime's per-protocol batch engines.
        self.create_backend = create_backend

    def _allocate_port(self) -> int:
        """reference: proxy.go allocatePort — linear scan from the range."""
        with self._mutex:
            for _ in range(self.port_max - self.port_min + 1):
                port = self._next
                self._next += 1
                if self._next > self.port_max:
                    self._next = self.port_min
                if port not in self.allocated_ports:
                    self.allocated_ports.add(port)
                    return port
        raise RuntimeError("proxy port range exhausted")

    def create_or_update_redirect(
        self, l4: L4Filter, proxy_id: str, endpoint_id: int
    ) -> Redirect:
        """reference: proxy.go:154 CreateOrUpdateRedirect."""
        with self._mutex:
            existing = self.redirects.get(proxy_id)
            if existing is not None:
                if existing.l7_parser != l4.l7_parser:
                    raise ValueError(
                        f"redirect {proxy_id} parser change "
                        f"{existing.l7_parser} -> {l4.l7_parser} not allowed"
                    )
                existing.l4_filter = l4
                # Rules or identity expansions may have changed: rebuild
                # the serving engine's compiled model (reference: updated
                # NPDS policy re-applied to the running proxy).
                if self.create_backend is not None:
                    existing.implementation = self.create_backend(existing)
                return existing
            port = self._allocate_port()
            r = Redirect(
                proxy_id=proxy_id,
                proxy_port=port,
                endpoint_id=endpoint_id,
                ingress=l4.ingress,
                l7_parser=l4.l7_parser,
                l4_filter=l4,
            )
            if self.create_backend is not None:
                r.implementation = self.create_backend(r)
            self.redirects[proxy_id] = r
            log.with_fields(proxyID=proxy_id, port=port,
                            parser=l4.l7_parser).debug("created redirect")
            return r

    def remove_redirect(self, proxy_id: str) -> bool:
        """reference: proxy.go RemoveRedirect."""
        with self._mutex:
            r = self.redirects.pop(proxy_id, None)
            if r is None:
                return False
            self.allocated_ports.discard(r.proxy_port)
        return True

    def remove_endpoint_redirects(self, endpoint_id: int) -> int:
        with self._mutex:
            dead = [pid for pid, r in self.redirects.items()
                    if r.endpoint_id == endpoint_id]
        for pid in dead:
            self.remove_redirect(pid)
        return len(dead)

    def get(self, proxy_id: str) -> Optional[Redirect]:
        return self.redirects.get(proxy_id)
