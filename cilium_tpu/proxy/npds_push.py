"""NPDS policy translation + push to the verdict service.

reference: pkg/envoy/server.go:607 getNetworkPolicy (an endpoint's
resolved L4Policy rendered as a ``cilium.NetworkPolicy``) and :628
UpdateNetworkPolicy (the versioned push to subscribed proxies).  Here
the proxy is the TPU verdict service: the daemon translates every
endpoint's resolved policy into the proxylib ``NetworkPolicy`` shape
and ships the FULL set over the sidecar wire on every change —
``Instance.policy_update`` swaps the whole policy map atomically, the
same full-state semantics as the reference's NPDS versioned cache.

Kafka filters are deliberately NOT translated: the reference serves
Kafka from the standalone Go proxy, not Envoy/NPDS (pkg/proxy/
proxy.go:229-236 dispatch), and this build mirrors that split — the
in-process Kafka batch engine owns those rules.
"""

from __future__ import annotations

import logging
import threading

from ..models.builder import expand_selector_remotes
from ..policy.l4 import PARSER_TYPE_HTTP, PARSER_TYPE_KAFKA, PARSER_TYPE_NONE
from ..proxylib.npds import (
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
)

log = logging.getLogger(__name__)


def endpoint_policy_name(ep) -> str:
    """The reference keys NPDS policies by endpoint IP (server.go:607);
    endpoints without one fall back to their id."""
    return ep.ipv4 or f"ep-{ep.id}"


def network_policy_for_endpoint(ep, identity_cache: dict) -> NetworkPolicy:
    """Render one endpoint's resolved ingress policy as the NPDS shape,
    expanding selectors against the identity cache exactly like the
    device-model builder (models/builder.py)."""
    port_policies: list[PortNetworkPolicy] = []
    l4 = ep.desired_l4_policy
    ingress_map = l4.ingress if l4 is not None else {}
    for f in ingress_map.values():
        if f.l7_parser == PARSER_TYPE_KAFKA:
            continue  # served by the in-process Kafka engine (see above)
        rules: list[PortNetworkPolicyRule] = []
        for sel, l7 in f.l7_rules_per_ep.items():
            remotes = expand_selector_remotes(sel, identity_cache)
            if remotes is not None and not remotes:
                # Selector currently matches NO identity: fail closed.
                continue
            rule = PortNetworkPolicyRule(
                remote_policies=sorted(remotes) if remotes else []
            )
            if f.l7_parser == PARSER_TYPE_HTTP:
                rule.http_rules = [
                    {
                        "method": h.method, "path": h.path, "host": h.host,
                        "headers": list(h.headers),
                    }
                    for h in l7.http
                ]
            elif f.l7_parser != PARSER_TYPE_NONE:
                rule.l7_proto = l7.l7proto or f.l7_parser
                rule.l7_rules = [dict(r) for r in l7.l7]
            rules.append(rule)
        port_policies.append(
            PortNetworkPolicy(
                port=int(f.port), protocol=f.protocol or "TCP", rules=rules
            )
        )
    return NetworkPolicy(
        name=endpoint_policy_name(ep),
        policy=ep.security_identity.id if ep.security_identity else 0,
        ingress_per_port_policies=port_policies,
    )


class NpdsPusher:
    """Keeps a verdict service's policy map in sync with the daemon's
    endpoint policies (reference: XDSServer.UpdateNetworkPolicy)."""

    def __init__(self, socket_path: str, ack_timeout: float = 5.0):
        from ..sidecar.client import SidecarClient

        # The client timeout IS the ACK deadline: policy_update blocks
        # until the service replies MSG_ACK or the deadline passes
        # (reference: completion deadline, pkg/endpoint/bpf.go:555).
        self.client = SidecarClient(socket_path, timeout=ack_timeout)
        self.module = self.client.open_module([])
        if self.module == 0:
            raise ConnectionError(f"verdict service at {socket_path}")
        self._policies: dict[str, NetworkPolicy] = {}
        # Serializes map mutation + full-state send: endpoint builds run
        # on several worker threads, and interleaved snapshot/send pairs
        # could deliver a stale final state to the service.
        self._mutex = threading.Lock()
        self.pushes = 0
        self.nacks = 0

    def upsert(self, ep, identity_cache: dict) -> bool:
        with self._mutex:
            self._policies[endpoint_policy_name(ep)] = (
                network_policy_for_endpoint(ep, identity_cache)
            )
            return self._push_locked()

    def remove(self, ep) -> bool:
        with self._mutex:
            if self._policies.pop(endpoint_policy_name(ep), None) is None:
                return True
            return self._push_locked()

    def _push_locked(self) -> bool:
        """Full-state push; NACK leaves the service's active map
        untouched (reference: xds/ack.go NACK handling)."""
        from ..proxylib.types import FilterResult

        res = self.client.policy_update(
            self.module, list(self._policies.values())
        )
        self.pushes += 1
        if res != int(FilterResult.OK):
            self.nacks += 1
            log.warning("NPDS push NACKed: %s", res)
            return False
        return True

    def close(self) -> None:
        self.client.close()
