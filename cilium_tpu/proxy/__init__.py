"""L7 proxy redirect management.

reference: pkg/proxy/proxy.go:59-236 — allocates proxy ports from the
configured range, tracks Redirect lifecycles keyed by proxy ID, and
dispatches by parser type.  In the reference, HTTP and proxylib protocols
go to Envoy and Kafka to the in-agent Go proxy; here every parser type maps
to a TPU batch engine registered for that L7 protocol
(cilium_tpu.runtime), all sharing the device verdict path.
"""

from .manager import ProxyManager, Redirect

__all__ = ["ProxyManager", "Redirect"]
