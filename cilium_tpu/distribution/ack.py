"""ACK-tracking resource mutator.

reference: pkg/envoy/xds/ack.go:86 AckingResourceMutatorWrapper — wraps
cache mutations so the caller's Completion completes only once every
targeted node has ACKed a version >= the mutation's; NACKs and stale ACKs
leave the completion pending (the endpoint regeneration then times out and
reverts, reference: pkg/endpoint/bpf.go:555).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..utils.completion import Completion
from .cache import Cache
from .server import DistributionServer


@dataclass
class _PendingCompletion:
    """reference: ack.go pendingCompletion."""

    completion: Completion
    type_url: str
    version: int
    remaining_nodes: set = field(default_factory=set)


class AckingMutator:
    """reference: ack.go:86."""

    def __init__(self, cache: Cache, server: DistributionServer) -> None:
        self.cache = cache
        self.server = server
        self._pending: list[_PendingCompletion] = []
        self._mutex = threading.Lock()
        server.add_ack_observer(self._on_ack)

    def upsert(
        self,
        type_url: str,
        name: str,
        resource: Any,
        node_ids: list[str],
        completion: Optional[Completion] = None,
    ) -> Callable[[], None]:
        """reference: ack.go Upsert; returns a revert function."""
        version, updated, revert = self.cache.upsert(
            type_url, name, resource, force=True
        )
        self._track(type_url, version, node_ids, completion)
        return revert or (lambda: None)

    def delete(
        self,
        type_url: str,
        name: str,
        node_ids: list[str],
        completion: Optional[Completion] = None,
    ) -> Callable[[], None]:
        version, updated, revert = self.cache.delete(type_url, name)
        self._track(type_url, version, node_ids, completion)
        return revert or (lambda: None)

    def _track(self, type_url, version, node_ids, completion) -> None:
        if completion is None:
            return
        # Nodes that already ACKed this or a later version don't count.
        remaining = {
            n for n in node_ids
            if self.server.node_acked_version(n, type_url) < version
        }
        if not remaining:
            completion.complete()
            return
        with self._mutex:
            self._pending.append(
                _PendingCompletion(
                    completion=completion,
                    type_url=type_url,
                    version=version,
                    remaining_nodes=remaining,
                )
            )

    def _on_ack(self, node_id: str, type_url: str, version: int,
                nack: bool) -> None:
        """reference: ack.go:138 HandleResourceVersionAck."""
        if nack:
            return
        done: list[_PendingCompletion] = []
        with self._mutex:
            for p in self._pending:
                if p.type_url == type_url and version >= p.version:
                    p.remaining_nodes.discard(node_id)
                    if not p.remaining_nodes:
                        done.append(p)
            self._pending = [p for p in self._pending if p.remaining_nodes]
        for p in done:
            p.completion.complete()

    def pending_count(self) -> int:
        with self._mutex:
            return len(self._pending)
