"""Versioned resource cache (reference: pkg/envoy/xds/cache.go).

Holds the most recent version of each named resource per type URL; every
transaction bumps the cache version and records, per resource, the version
it last changed in — so a subscriber at version V receives exactly the
resources modified since V.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


@dataclass
class VersionedResources:
    """reference: xds/stream.go VersionedResources."""

    version: int
    type_url: str
    resources: dict[str, Any]  # name -> resource (full current set)
    removed: list[str] = field(default_factory=list)


class Cache:
    """reference: xds/cache.go:34 Cache."""

    def __init__(self) -> None:
        # type_url -> name -> (resource, last_modified_version)
        self._resources: dict[str, dict[str, tuple[Any, int]]] = {}
        self.version = 1
        self._mutex = threading.RLock()
        self._observers: list[Callable[[str, int], None]] = []

    def add_observer(self, observer: Callable[[str, int], None]) -> None:
        """observer(type_url, new_version) on every change."""
        self._observers.append(observer)

    def tx(
        self,
        type_url: str,
        upserted: dict[str, Any],
        deleted: list[str] | None = None,
        force: bool = False,
    ) -> tuple[int, bool, Optional[Callable[[], None]]]:
        """Atomic transaction (reference: cache.go:79): returns
        (version, updated, revert)."""
        deleted = deleted or []
        with self._mutex:
            table = self._resources.setdefault(type_url, {})
            new_version = self.version + 1

            # Determine effective changes.
            revert_upserts: dict[str, tuple[Any, int]] = {}
            revert_deletes: dict[str, tuple[Any, int]] = {}
            changed = False
            for name, res in upserted.items():
                old = table.get(name)
                if old is not None and old[0] == res and not force:
                    continue
                if old is not None:
                    revert_upserts[name] = old
                else:
                    revert_upserts[name] = (None, 0)
                table[name] = (res, new_version)
                changed = True
            for name in deleted:
                old = table.pop(name, None)
                if old is not None:
                    revert_deletes[name] = old
                    changed = True

            if not changed and not force:
                return self.version, False, None
            self.version = new_version
            observers = list(self._observers)

            def revert() -> None:
                with self._mutex:
                    t = self._resources.setdefault(type_url, {})
                    rv = self.version + 1
                    for name, (res, _) in revert_upserts.items():
                        if res is None:
                            t.pop(name, None)
                        else:
                            t[name] = (res, rv)
                    for name, (res, _) in revert_deletes.items():
                        t[name] = (res, rv)
                    self.version = rv
                    obs = list(self._observers)
                for o in obs:
                    o(type_url, rv)

        for o in observers:
            o(type_url, new_version)
        return new_version, True, revert

    def upsert(self, type_url: str, name: str, resource: Any,
               force: bool = False):
        """reference: cache.go:175 Upsert."""
        return self.tx(type_url, {name: resource}, force=force)

    def delete(self, type_url: str, name: str):
        return self.tx(type_url, {}, [name])

    def clear(self, type_url: str):
        with self._mutex:
            names = list(self._resources.get(type_url, {}))
        return self.tx(type_url, {}, names)

    def lookup(self, type_url: str, name: str) -> Optional[Any]:
        with self._mutex:
            entry = self._resources.get(type_url, {}).get(name)
            return entry[0] if entry else None

    def get_resources(
        self, type_url: str, since_version: int = 0,
        names: list[str] | None = None,
    ) -> Optional[VersionedResources]:
        """Current resources if anything changed after since_version, else
        None (reference: cache.go GetResources)."""
        with self._mutex:
            table = self._resources.get(type_url, {})
            if names is not None:
                table = {n: table[n] for n in names if n in table}
            if not table and since_version == 0:
                # Nothing ever published: no initial delivery.
                return None
            newest = max((v for _, v in table.values()), default=self.version)
            if newest <= since_version:
                return None
            return VersionedResources(
                version=self.version,
                type_url=type_url,
                resources={n: r for n, (r, _) in table.items()},
            )
