"""Policy distribution: versioned resource cache with ACK-tracked pushes.

reference: pkg/envoy/xds — the agent embeds an xDS-protocol server over a
unix socket pushing NPDS (per-endpoint NetworkPolicy) and NPHDS
(IP->identity) resources to proxies; a versioned Cache (xds/cache.go:34)
holds the latest resources, subscription streams deliver updates, and an
ACK-tracking mutator (xds/ack.go:86) completes Completions only when every
targeted proxy has acknowledged the version — policy application blocks on
this (pkg/endpoint/bpf.go:555).

Here the proxies are the in-process TPU batch engines and the native
runtime shim; streams are in-process queues, with a unix-socket JSON
framing for out-of-process subscribers (cilium_tpu.distribution.sock).
"""

from .cache import Cache, VersionedResources
from .ack import AckingMutator
from .server import DistributionServer, Subscription

# Cilium resource type URLs (reference: pkg/envoy/server.go typeURLs).
TYPE_NETWORK_POLICY = "type.googleapis.com/cilium.NetworkPolicy"
TYPE_NETWORK_POLICY_HOSTS = "type.googleapis.com/cilium.NetworkPolicyHosts"

__all__ = [
    "AckingMutator",
    "Cache",
    "DistributionServer",
    "Subscription",
    "TYPE_NETWORK_POLICY",
    "TYPE_NETWORK_POLICY_HOSTS",
    "VersionedResources",
]
