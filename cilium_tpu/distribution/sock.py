"""Unix-socket transport for distribution streams.

The out-of-process seam: where the reference speaks gRPC xDS over a unix
socket to Envoy (reference: pkg/envoy/server.go:67 XDSServer socket), this
speaks length-prefixed JSON frames over a unix socket to native sidecars
(the C++ runtime shim).  Protocol:

  client -> server: {"subscribe": {"node": ..., "type_url": ...}}
                    {"ack": {"version": N, "nack": false}}
  server -> client: {"version": N, "type_url": ..., "resources": {...}}

Each frame is a 4-byte big-endian length followed by UTF-8 JSON.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading

from ..utils.logging import get_logger
from ..utils.sockutil import shutdown_close
from .server import DistributionServer

log = get_logger("distribution-sock")


def send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock: socket.socket) -> dict | None:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    if n > 64 * 1024 * 1024:
        raise ValueError(f"frame too large: {n}")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body.decode())


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class SocketDistributionServer:
    """Accepts sidecar subscriptions over a unix socket."""

    def __init__(self, server: DistributionServer, path: str) -> None:
        self.server = server
        self.path = path
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(16)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="dist-sock", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        sub = None
        try:
            conn.settimeout(None)
            msg = recv_frame(conn)
            if not msg or "subscribe" not in msg:
                return
            sub = self.server.subscribe(
                msg["subscribe"]["node"], msg["subscribe"]["type_url"]
            )
            sender = threading.Thread(
                target=self._send_loop, args=(conn, sub), daemon=True
            )
            sender.start()
            while True:
                msg = recv_frame(conn)
                if msg is None:
                    return
                if "ack" in msg:
                    self.server.ack(
                        sub,
                        msg["ack"].get("version", 0),
                        nack=msg["ack"].get("nack", False),
                    )
        except (OSError, ValueError) as e:
            log.with_field("error", str(e)).debug("sidecar stream closed")
        finally:
            if sub is not None:
                self.server.unsubscribe(sub)
            # shutdown first: the per-subscriber _send_loop thread may
            # be inside send_frame on this socket — a bare close would
            # defer the teardown until its next write.
            shutdown_close(conn)

    def _send_loop(self, conn: socket.socket, sub) -> None:
        try:
            while not self._stop.is_set():
                vr = sub.next(timeout=0.2)
                if vr is None:
                    continue
                send_frame(conn, {
                    "version": vr.version,
                    "type_url": vr.type_url,
                    "resources": vr.resources,
                })
        except OSError:
            pass

    def close(self) -> None:
        self._stop.set()
        # Wake the acceptor parked on the listener; see R3.
        try:
            shutdown_close(self._sock)
        finally:
            if os.path.exists(self.path):
                os.unlink(self.path)
