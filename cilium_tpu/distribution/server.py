"""Subscription streams and ACK bookkeeping.

reference: pkg/envoy/xds/server.go — per-(node, typeURL) subscription
streams: the server sends the current versioned resource set whenever it
changes; the client responds with an ACK naming the version it applied (or
a NACK repeating the old version).  The ACK observers drive the acking
mutator's completions.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from .cache import Cache, VersionedResources


@dataclass
class Subscription:
    node_id: str
    type_url: str
    events: "queue.Queue[VersionedResources]" = field(
        default_factory=lambda: queue.Queue()
    )
    last_sent: int = 0
    last_acked: int = 0

    def next(self, timeout: float | None = None) -> Optional[VersionedResources]:
        try:
            return self.events.get(timeout=timeout)
        except queue.Empty:
            return None


class DistributionServer:
    """reference: pkg/envoy/xds/server.go Server + ack observers."""

    def __init__(self, cache: Cache) -> None:
        self.cache = cache
        self._subs: list[Subscription] = []
        self._mutex = threading.RLock()
        # ack observers: (node_id, type_url, acked_version, nack)
        self._ack_observers: list[Callable[[str, str, int, bool], None]] = []
        cache.add_observer(self._on_cache_change)

    def add_ack_observer(self, obs: Callable[[str, str, int, bool], None]) -> None:
        self._ack_observers.append(obs)

    def subscribe(self, node_id: str, type_url: str) -> Subscription:
        """Open a stream; the current state is delivered immediately
        (reference: server.go initial versioned response)."""
        sub = Subscription(node_id=node_id, type_url=type_url)
        with self._mutex:
            self._subs.append(sub)
        current = self.cache.get_resources(type_url, since_version=0)
        if current is not None:
            sub.last_sent = current.version
            sub.events.put(current)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._mutex:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    def _on_cache_change(self, type_url: str, version: int) -> None:
        with self._mutex:
            subs = [s for s in self._subs if s.type_url == type_url]
        for sub in subs:
            vr = self.cache.get_resources(type_url, since_version=sub.last_sent)
            if vr is not None:
                sub.last_sent = vr.version
                sub.events.put(vr)

    def ack(self, sub: Subscription, version: int, nack: bool = False) -> None:
        """Client acknowledgement (reference: xds/ack.go HandleResourceVersionAck)."""
        if not nack:
            sub.last_acked = max(sub.last_acked, version)
        for obs in list(self._ack_observers):
            obs(sub.node_id, sub.type_url, version, nack)

    def node_acked_version(self, node_id: str, type_url: str) -> int:
        with self._mutex:
            return max(
                (s.last_acked for s in self._subs
                 if s.node_id == node_id and s.type_url == type_url),
                default=0,
            )
