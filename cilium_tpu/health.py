"""cilium-health analog: per-node responder + cluster-wide prober.

reference: pkg/health/server/{server.go:82,prober.go:40} + cilium-health
— every node runs a small health endpoint; one prober per agent probes
every known node (and optionally its health endpoint twin) over TCP,
keeping per-node connectivity status and latency that `cilium status`
surfaces.  The reference probes ICMP + the health HTTP port; raw ICMP
needs privileges, so here both probes are TCP connects (the L3 reach
probe connects to the node's health port; the "endpoint" probe targets
the per-node secondary port, matching the reference's node-IP vs
health-endpoint-IP distinction).
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass, field

from .utils.controller import ControllerManager, ControllerParams
from .utils.sockutil import shutdown_close

DEFAULT_PROBE_INTERVAL = 10.0  # reference: server.go ProbeInterval 10s
PROBE_TIMEOUT = 1.0


class HealthResponder:
    """The per-node health endpoint (reference: cilium-health daemon's
    listener): accepts a TCP connect and echoes one status byte."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address = "%s:%d" % self._sock.getsockname()[:2]
        self._stopped = False
        threading.Thread(
            target=self._loop, daemon=True, name="health-responder"
        ).start()

    def _loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.sendall(b"\x01")
            except OSError:
                pass
            finally:
                # A connect-and-close prober RSTs before our shutdown;
                # close must still run or each such probe leaks one fd
                # until accept() dies with EMFILE.
                shutdown_close(conn)

    def close(self) -> None:
        self._stopped = True
        # shutdown() wakes the blocked accept(); close() alone leaves
        # the listener live (and serving!) until the next connection.
        shutdown_close(self._sock)


@dataclass
class PathStatus:
    """reference: models.PathStatus/ConnectivityStatus."""

    reachable: bool = False
    latency_ns: int = 0
    last_probed: float = 0.0
    failures: int = 0


@dataclass
class NodeHealth:
    name: str
    address: str
    status: PathStatus = field(default_factory=PathStatus)


class Prober:
    """Probes every registered node periodically (prober.go:40 runProbe);
    degraded nodes keep their last status with a failure count."""

    def __init__(self, node_name: str = "local",
                 interval: float = DEFAULT_PROBE_INTERVAL,
                 controllers: ControllerManager | None = None) -> None:
        self.node_name = node_name
        self.interval = interval
        self._nodes: dict[str, NodeHealth] = {}
        self._mutex = threading.Lock()
        self._controllers = controllers or ControllerManager()
        self._own_controllers = controllers is None
        self._started = False

    # -- node registry (fed by node discovery / clustermesh) --------------

    def add_node(self, name: str, address: str) -> None:
        with self._mutex:
            self._nodes[name] = NodeHealth(name=name, address=address)

    def remove_node(self, name: str) -> bool:
        with self._mutex:
            return self._nodes.pop(name, None) is not None

    # -- probing -----------------------------------------------------------

    def start(self) -> "Prober":
        if not self._started:
            self._started = True
            self._controllers.update_controller(
                "health-prober",
                ControllerParams(do_func=self.probe_all,
                                 run_interval=self.interval),
            )
        return self

    def probe_all(self) -> None:
        """One probe cycle over a snapshot of the node set."""
        with self._mutex:
            nodes = list(self._nodes.values())
        for node in nodes:
            self._probe(node)

    def _probe(self, node: NodeHealth) -> None:
        host, _, port = node.address.rpartition(":")
        t0 = time.perf_counter_ns()
        try:
            with socket.create_connection(
                (host, int(port)), timeout=PROBE_TIMEOUT
            ) as s:
                s.recv(1)
            latency = time.perf_counter_ns() - t0
            ok = True
        except (OSError, ValueError):
            latency = 0
            ok = False
        with self._mutex:
            cur = self._nodes.get(node.name)
            if cur is None:
                return
            st = cur.status
            st.reachable = ok
            st.last_probed = time.time()
            if ok:
                st.latency_ns = latency
                st.failures = 0
            else:
                st.failures += 1

    # -- status ------------------------------------------------------------

    def get_status(self) -> dict:
        """reference: GET /status — per-node connectivity."""
        with self._mutex:
            nodes = {
                n.name: {
                    "address": n.address,
                    "reachable": n.status.reachable,
                    "latency_ms": round(n.status.latency_ns / 1e6, 3),
                    "failures": n.status.failures,
                    "last_probed": n.status.last_probed,
                }
                for n in self._nodes.values()
            }
        degraded = [k for k, v in nodes.items() if not v["reachable"]]
        return {
            "probed_nodes": len(nodes),
            "degraded": degraded,
            "healthy": len(nodes) - len(degraded),
            "nodes": nodes,
        }

    def close(self) -> None:
        if self._own_controllers:
            self._controllers.remove_all()
        else:
            self._controllers.remove_controller("health-prober")
