"""The agent daemon: wires every subsystem into one running node agent.

reference: daemon/ — NewDaemon (daemon.go:1090) constructs the policy
repository, identity allocator, ipcache watcher, endpoint builders, proxy
support and API servers; runDaemon (main.go:837) brings the node online.
"""

from .daemon import Daemon

__all__ = ["Daemon"]
