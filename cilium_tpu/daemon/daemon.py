"""Daemon core (reference: daemon/daemon.go NewDaemon + daemon/policy.go).

Construction order mirrors the reference's bootstrap (daemon.go:1090):
struct-alignment check, kvstore client, policy repository, endpoint
builders, identity allocator (owner callback -> policy recalc trigger),
ipcache watcher feeding the datapath map, proxy support, distribution
server, monitor, access log, status controllers, endpoint restore.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..accesslog import AccessLogger
from ..alignchecker import check_struct_alignments
from ..datapath import PreFilter
from ..distribution import (
    AckingMutator,
    Cache,
    DistributionServer,
    TYPE_NETWORK_POLICY,
    TYPE_NETWORK_POLICY_HOSTS,
)
from ..endpoint import BuildQueue, Endpoint, EndpointManager, EndpointState
from ..identity import IdentityAllocator
from ..ipcache import (
    IPIdentityCache,
    IPIdentityPair,
    KvstoreIPSync,
    datapath_listener,
)
from ..kvstore import (
    FileBackend,
    KvstoreError,
    LocalBackend,
    LockError,
    NetBackend,
    setup_client,
)
from ..kvstore.allocator import AllocatorError
from ..labels import Labels, LabelArray
from ..maps import CtMap, IpcacheMap, LbMap, MetricsMap
from ..monitor import (
    AGENT_NOTIFY_KVSTORE_DEGRADED,
    AGENT_NOTIFY_KVSTORE_RESTORED,
    AGENT_NOTIFY_POLICY_UPDATED,
    AGENT_NOTIFY_START,
    Monitor,
)
from ..policy import Repository, Rule, SearchContext, Tracing, init_entities
from ..proxy import ProxyManager
from ..sidecar import blackbox
from ..utils import defaults
from ..utils.controller import ControllerManager, ControllerParams
from ..utils.logging import get_logger
from ..utils.metrics import (
    EndpointCount,
    KvstoreDegraded,
    KvstoreDegradedEvents,
    PolicyCount,
    PolicyImportErrors,
    PolicyRevision,
    registry as metrics_registry,
)
from ..utils import option as option_mod
from ..utils.option import DaemonConfig
from ..utils.trigger import Trigger

log = get_logger("daemon")


class Daemon:
    """reference: daemon/daemon.go Daemon."""

    def __init__(self, config: DaemonConfig | None = None,
                 node_name: str = "local") -> None:
        self.config = config or DaemonConfig()
        self.config.validate()
        # Install as the process-global config: endpoints and other
        # subsystems consult option.config (reference: option.Config
        # singleton populated from flags).
        option_mod.config = self.config
        check_struct_alignments()  # reference: daemon bootstrap align check
        init_entities(self.config.cluster_name)

        self.node_name = node_name
        self.controllers = ControllerManager()

        # kvstore (reference: kvstore.Client setup; "tcp" is the
        # networked backend — the etcd-module analog)
        if self.config.kvstore == "file":
            path = self.config.kvstore_opts.get(
                "path", os.path.join(self.config.run_dir, "kvstore.json")
            )
            self.kvstore = FileBackend(path)
        elif self.config.kvstore == "tcp":
            self.kvstore = NetBackend(self.config.kvstore_opts["address"])
        else:
            self.kvstore = LocalBackend()
        setup_client(self.kvstore)
        # Degraded-mode latch (reference: the agent keeps the datapath
        # up on cached state when etcd flaps — kvstore connectivity is
        # a status condition, not a crash): endpoint regeneration and
        # verdict serving continue on cached identities while the
        # store is fenced or unreachable.
        self._kvstore_degraded = False
        self._kv_degraded_lock = threading.Lock()

        # Policy repository (reference: policy.NewPolicyRepository)
        self.policy = Repository()
        self._cidr_identities: dict[str, object] = {}

        # Endpoint management + builders (reference: daemon.go:238)
        self.endpoint_manager = EndpointManager()
        workers = max(defaults.MIN_ENDPOINT_BUILDERS, os.cpu_count() or 1)
        self.build_queue = BuildQueue(
            self._build_endpoint, workers=workers
        )

        # Regeneration trigger folding policy events (reference:
        # TriggerPolicyUpdates + pkg/trigger)
        self.policy_trigger = Trigger(
            self._trigger_policy_updates_now,
            min_interval=0.05,
            name="policy-regen",
        )

        # Identity allocation (reference: identity.InitIdentityAllocator)
        self.identity_allocator = IdentityAllocator(
            owner_notify=self.policy_trigger.trigger,
            backend=self.kvstore,
            node_name=node_name,
        )

        # ipcache + datapath map (reference: ipcache.InitIPIdentityWatcher)
        self.ipcache = IPIdentityCache(self.config.cluster_name)
        self.ipcache_map = IpcacheMap()
        self.ipcache.add_listener(datapath_listener(self.ipcache_map))
        self.ipcache_sync = KvstoreIPSync(self.ipcache, backend=self.kvstore)
        self.ipcache_sync.start_watcher()

        # Node registry: publish the local node, track peers (reference:
        # node.AutoComplete + the pkg/node kvstore store; remote nodes
        # are what the overlay encaps toward and what the health prober
        # probes).  Health attrs exist BEFORE the watch starts: node
        # events fire from the watcher thread immediately.
        self.health_responder = None
        self.health_prober = None
        from ..node import Node, NodeDiscovery

        self.node_discovery = NodeDiscovery(
            Node(
                name=node_name,
                cluster=self.config.cluster_name,
                ipv4_address=self.config.node_ipv4,
            ),
            backend=self.kvstore,
            on_node_update=self._on_remote_node,
            on_node_delete=self._on_remote_node_gone,
        )

        # Other datapath maps
        self.ct_map = CtMap()
        self.lb_map = LbMap()
        self.metrics_map = MetricsMap()
        self.prefilter = PreFilter()

        # Services / load-balancer control plane: programs the LbMap
        # from the REST API and the k8s watcher, with RevNAT ids
        # allocated cluster-wide through the kvstore (reference:
        # daemon/loadbalancer.go + pkg/service/id_kvstore.go).
        from ..service import ServiceManager

        self.service_manager = ServiceManager(self.lb_map, self.kvstore)

        # Proxy + runtime engines (reference: proxy.StartProxySupport)
        self.proxy_manager = ProxyManager(
            self.config.proxy_port_min,
            self.config.proxy_port_max,
            create_backend=self._create_proxy_backend,
        )

        # Policy distribution (reference: envoy.StartXDSServer)
        self.dist_cache = Cache()
        self.dist_server = DistributionServer(self.dist_cache)
        self.acking_mutator = AckingMutator(self.dist_cache, self.dist_server)

        # Monitor + access log
        self.monitor = Monitor(self.config.monitor_queue_size)
        # Flow-record ring (flowlog/): the datapath accounting pass and
        # the daemon-side L7 engines feed it; POLICY-VERDICT monitor
        # events ride the PolicyVerdictNotification runtime option.
        from ..flowlog import FlowLog

        self.flowlog = (
            FlowLog(
                capacity=self.config.flowlog_ring,
                opts=self.config.opts,
                monitor=self.monitor,
            )
            if self.config.flow_observe else None
        )
        self.access_logger = AccessLogger(
            endpoint_lookup=self.endpoint_manager.lookup,
            notify=lambda rec: self.monitor.notify(
                _accesslog_event(rec)
            ),
        )

        # cilium-health: per-node responder + cluster prober
        # (reference: daemon/main.go:926-968 health endpoint launch)
        if self.config.enable_health:
            from ..health import HealthResponder, Prober

            self.health_responder = HealthResponder()
            self.health_prober = Prober(
                node_name=node_name, controllers=self.controllers
            )
            self.health_prober.add_node(
                node_name, self.health_responder.address
            )
            self.health_prober.start()
            # Advertise the responder address cluster-wide and probe
            # every peer already discovered (reference: the health IP
            # travels in the Node object, prober.go probes all nodes).
            self.node_discovery.update_local(
                ipv4_health_ip=self.health_responder.address
            )
            for n in self.node_discovery.get_nodes().values():
                self._on_remote_node(n)

        # DNS poller slot for toFQDNs rules (started on demand with a
        # resolver via start_dns_poller; reference: daemon.go:1334
        # fqdn.StartDNSPoller)
        self.dns_poller = None

        # NPDS push target (attach_verdict_service connects it;
        # reference: the agent-embedded xDS server's policy stream)
        self.npds_pusher = None

        # Opt-in profiling + per-flow debug gates (reference: --pprof
        # -> pkg/pprof.Enable, pkg/flowdebug.Enable from initEnv)
        self.pprof_server = None
        if self.config.pprof:
            from ..utils import pprofserve

            self.pprof_server = pprofserve.enable(
                ("127.0.0.1", self.config.pprof_port)
            )
        if self.config.per_flow_debug:
            from ..utils import flowdebug

            flowdebug.enable()

        # Controllers (reference: pkg/controller usage across the daemon)
        self.controllers.update_controller(
            "metrics-sync",
            ControllerParams(do_func=self._sync_metrics, run_interval=5.0),
        )
        self.controllers.update_controller(
            "ct-gc",
            ControllerParams(do_func=lambda: self.ct_map.gc(),
                             run_interval=30.0),
        )
        self.controllers.update_controller(
            "identity-gc",
            ControllerParams(do_func=lambda: self.identity_allocator.gc(),
                             run_interval=300.0),
        )
        # Retry endpoints stranded not-ready by a failed proxy-ACK gate
        # (transient NPDS NACK/timeout with the service still attached)
        # — the reference's endpoint regeneration controller role.
        self.controllers.update_controller(
            "endpoint-regen-retry",
            ControllerParams(do_func=self._retry_not_ready_endpoints,
                             run_interval=15.0),
        )
        # Store liveness probe driving the degraded-mode latch both
        # ways (a flapless exit path: no endpoint churn is needed to
        # notice the store came back).
        self.controllers.update_controller(
            "kvstore-health",
            ControllerParams(do_func=self._check_kvstore_health,
                             run_interval=5.0),
        )

        # Initialize the accelerator backend once, on this thread, before
        # builder threads race to first-touch it (concurrent first jax use
        # from several threads is slow and can wedge plugin backends).
        if not self.config.dry_mode:
            try:
                import jax

                dev = jax.devices()[0]
                log.with_field("device", str(dev)).info("device backend ready")
            except Exception as e:  # noqa: BLE001 — degraded host-only mode
                log.with_field("error", str(e)).warning(
                    "no accelerator available; host-side verdicts only"
                )

        self._started = time.time()
        self.monitor.send_agent_notification(
            AGENT_NOTIFY_START, f"cilium-tpu agent started on {node_name}"
        )

        if self.config.restore_state:
            self.restore_endpoints()

    # -- EndpointOwner protocol -------------------------------------------

    def get_policy_repository(self) -> Repository:
        return self.policy

    def get_identity_cache(self):
        return self.identity_allocator.get_identity_cache()

    def get_proxy_manager(self) -> ProxyManager:
        return self.proxy_manager

    def update_network_policy(self, ep: Endpoint) -> bool:
        """ACK-gated proxy policy push, called from inside
        Endpoint.regenerate (reference: pkg/endpoint/policy.go:402 →
        envoy.UpdateNetworkPolicy, blocking on the xDS ACK completion,
        bpf.go:555).  No verdict service attached = vacuous ACK (the
        reference likewise skips the wait with no proxy redirects).
        Returns False on push failure, NACK, or timeout — the endpoint
        then reverts and reports not-ready."""
        if self.npds_pusher is None:
            return True
        try:
            return self.npds_pusher.upsert(
                ep, self.identity_allocator.get_identity_cache()
            )
        except (OSError, TimeoutError):
            log.with_field("ep", ep.id).warning(
                "NPDS push failed; verdict service unreachable — "
                "regeneration will revert"
            )
            return False

    # -- kvstore degraded mode ---------------------------------------------

    def _enter_kvstore_degraded(self, reason: str) -> None:
        with self._kv_degraded_lock:
            if self._kvstore_degraded:
                return
            self._kvstore_degraded = True
        KvstoreDegraded.set(1)
        KvstoreDegradedEvents.inc()
        # Fail-closed marker: lands in every installed flight recorder
        # (the daemon has no recorder of its own — a co-hosted verdict
        # service's ring is where the incident timeline lives).
        blackbox.broadcast_mark("kvstore_degraded", reason=reason)
        log.with_field("reason", reason).warning(
            "kvstore degraded: continuing on cached identities"
        )
        self.monitor.send_agent_notification(
            AGENT_NOTIFY_KVSTORE_DEGRADED,
            f"kvstore degraded ({reason}); serving cached identities",
        )

    def _exit_kvstore_degraded(self) -> None:
        with self._kv_degraded_lock:
            if not self._kvstore_degraded:
                return
            self._kvstore_degraded = False
        KvstoreDegraded.set(0)
        blackbox.broadcast_mark("kvstore_restored")
        log.info("kvstore connectivity restored")
        self.monitor.send_agent_notification(
            AGENT_NOTIFY_KVSTORE_RESTORED, "kvstore connectivity restored"
        )

    def _check_kvstore_health(self) -> None:
        """The only path OUT of degraded mode.  Reachability is not
        enough: a fenced or still-replicating server answers pings and
        reads while rejecting every write — the probe must check
        WRITABILITY (role + fencing state), or the latch would flap
        'restored' while allocations still fail."""
        b = self.kvstore
        ping = getattr(b, "ping", None)
        if not callable(ping):
            return  # local/file backends cannot flap
        if not ping():
            self._enter_kvstore_degraded("store unreachable")
            return
        info_fn = getattr(b, "server_info", None)
        if callable(info_fn):
            try:
                info = info_fn()
            except KvstoreError as e:
                self._enter_kvstore_degraded(f"status probe: {e}")
                return
            if info.get("fenced") or info.get("role") != "primary":
                self._enter_kvstore_degraded(
                    f"store {info.get('address')} not writable "
                    f"(role={info.get('role')}, "
                    f"fenced={info.get('fenced')})"
                )
                return
        self._exit_kvstore_degraded()

    def _allocate_identity(self, lbls: Labels):
        """Identity allocation with graceful degradation: a fenced or
        unreachable store must not stop endpoint regeneration — labels
        already resolved keep their cached identity (cluster-unique by
        construction when it was allocated), with a LOCAL refcounted
        reference so the eventual release balances; only a truly NEW
        label set fails while degraded.  Exiting degraded mode is the
        health probe's job — a cache-served allocation proves nothing
        about connectivity."""
        try:
            return self.identity_allocator.allocate(lbls)
        except (LockError, AllocatorError):
            # KvstoreError subclasses that do NOT mean the store is
            # down (lock contention, ID-space exhaustion): latching
            # degraded mode for them would flap the gauge and spam
            # monitor notifications while the store is healthy.
            raise
        except KvstoreError as e:
            cached = self.identity_allocator.retain_cached(lbls)
            self._enter_kvstore_degraded(f"identity allocation: {e}")
            if cached is None:
                raise
            return cached, False

    def _kvstore_publish(self, desc: str, fn) -> None:
        """Best-effort kvstore propagation (ipcache pairs etc.): local
        datapath state is already updated by the caller; a degraded
        store defers only the CROSS-NODE announcement.  Lock
        contention and allocator-domain errors are not connectivity
        loss — they propagate instead of latching degraded mode."""
        try:
            fn()
        except (LockError, AllocatorError):
            raise
        except KvstoreError as e:
            self._enter_kvstore_degraded(f"{desc}: {e}")

    # -- proxy backends ----------------------------------------------------

    def _create_proxy_backend(self, redirect):
        """Instantiate the runtime batch engine for a redirect; wired to
        the per-protocol model builders (reference dispatch:
        pkg/proxy/proxy.go:229-236)."""
        from ..runtime.engines import create_engine_for_redirect

        return create_engine_for_redirect(self, redirect)

    # -- endpoint lifecycle ------------------------------------------------

    def _build_endpoint(self, ep: Endpoint) -> None:
        ok = ep.regenerate(self, "policy update")
        if ok:
            self._push_endpoint_policy(ep)
            if not self.config.dry_mode:
                ep.write_state(self._state_dir())

    def _local_pair(self, ipv4: str, identity_id: int) -> IPIdentityPair:
        """The kvstore pair for a local endpoint IP: carries this node's
        underlay address so remote nodes learn where to encap
        (reference: pkg/ipcache/kvstore.go hostIP marshalling;
        consumed by the overlay path, bpf/lib/encap.h)."""
        import ipaddress

        tunnel = 0
        if self.config.node_ipv4:
            tunnel = int(ipaddress.IPv4Address(self.config.node_ipv4))
        return IPIdentityPair(
            ipv4, identity_id,
            tunnel_endpoint=tunnel, host_ip=self.config.node_ipv4,
        )

    def _on_remote_node(self, node) -> None:
        """Node discovery -> health prober feed (reference: the prober
        walks the discovered node set, pkg/health/server/prober.go:40)."""
        if self.health_prober is not None and node.ipv4_health_ip:
            self.health_prober.add_node(node.fullname(), node.ipv4_health_ip)

    def _on_remote_node_gone(self, name: str) -> None:
        if self.health_prober is not None:
            self.health_prober.remove_node(name)

    def _retry_not_ready_endpoints(self) -> None:
        """Re-enqueue endpoints that failed their last regeneration
        (e.g. proxy-ACK timeout) so policy converges without waiting
        for an unrelated policy event (reference: controller-driven
        endpoint regeneration retries with backoff)."""
        for ep in self.endpoint_manager.get_endpoints():
            if ep.state == EndpointState.NOT_READY:
                ep.set_state(
                    EndpointState.WAITING_TO_REGENERATE, "regen retry"
                )
                self.build_queue.enqueue(ep, key=ep.id)

    def attach_verdict_service(self, socket_path: str):
        """Connect the NPDS push to a live verdict service and sync the
        current endpoint policies (reference: daemon.go:1327
        StartProxySupport → envoy.StartXDSServer; here the daemon dials
        the service's socket instead of serving gRPC)."""
        from ..proxy.npds_push import NpdsPusher

        if self.npds_pusher is not None:
            self.npds_pusher.close()
        self.npds_pusher = NpdsPusher(
            socket_path, ack_timeout=self.config.proxy_ack_timeout_s
        )
        cache = self.identity_allocator.get_identity_cache()
        for ep in self.endpoint_manager.get_endpoints():
            if ep.desired_l4_policy is not None:
                self.npds_pusher.upsert(ep, cache)
        # Recovery: endpoints that failed their ACK gate while the
        # service was down regenerate now that it is back (reference:
        # the endpoint regeneration controller retries after proxy
        # completion timeouts).
        for ep in self.endpoint_manager.get_endpoints():
            if ep.state == EndpointState.NOT_READY:
                ep.set_state(
                    EndpointState.WAITING_TO_REGENERATE,
                    "verdict service restored",
                )
                self.build_queue.enqueue(ep, key=ep.id)
        return self.npds_pusher

    def _push_endpoint_policy(self, ep: Endpoint) -> None:
        """Publish the endpoint's resolved policy to the distribution
        cache (reference: pkg/envoy/server.go:628 UpdateNetworkPolicy).
        The verdict-service NPDS push itself happens ACK-gated INSIDE
        regeneration (update_network_policy above) — by the time an
        endpoint reaches ready, the service has acknowledged."""
        if ep.desired_l4_policy is None:
            return
        resource = {
            "endpoint_id": ep.id,
            "policy_revision": ep.policy_revision,
            "ingress_enforced": ep.ingress_policy_enabled,
            "egress_enforced": ep.egress_policy_enabled,
            "redirects": dict(ep.realized_redirects),
        }
        self.dist_cache.upsert(
            TYPE_NETWORK_POLICY, str(ep.id), resource, force=False
        )

    def endpoint_create(
        self, endpoint_id: int, ipv4: str = "",
        labels: list[str] | None = None, container_name: str = "",
    ) -> Endpoint:
        """reference: daemon/endpoint.go createEndpoint."""
        if self.endpoint_manager.lookup(endpoint_id) is not None:
            raise ValueError(f"endpoint {endpoint_id} already exists")
        ep = Endpoint(
            endpoint_id, ipv4=ipv4, container_name=container_name,
            labels=Labels.from_model(labels or []),
        )
        ep.set_state(EndpointState.WAITING_FOR_IDENTITY, "created")
        identity, _ = self._allocate_identity(
            ep.labels if ep.labels else Labels.from_model(["reserved:init"])
        )
        ep.set_identity(identity)
        self.endpoint_manager.insert(ep)
        EndpointCount.set(len(self.endpoint_manager))
        if ipv4:
            self.ipcache.upsert(ipv4, identity.id)
            self._kvstore_publish(
                "ipcache upsert",
                lambda: self.ipcache_sync.upsert_to_kvstore(
                    self._local_pair(ipv4, identity.id)
                ),
            )
        ep.set_state(EndpointState.WAITING_TO_REGENERATE, "identity ready")
        self.build_queue.enqueue(ep, key=ep.id)
        return ep

    def endpoint_delete(self, endpoint_id: int) -> bool:
        """reference: daemon/endpoint.go deleteEndpoint."""
        ep = self.endpoint_manager.lookup(endpoint_id)
        if ep is None:
            return False
        ep.set_state(EndpointState.DISCONNECTING, "delete")
        self.proxy_manager.remove_endpoint_redirects(endpoint_id)
        if ep.ipv4:
            self.ipcache.delete(ep.ipv4)
            self._kvstore_publish(
                "ipcache delete",
                lambda: self.ipcache_sync.delete_from_kvstore(ep.ipv4),
            )
        if ep.security_identity is not None:
            self._kvstore_publish(
                "identity release",
                lambda: self.identity_allocator.release(
                    ep.security_identity
                ),
            )
        self.endpoint_manager.remove(ep)
        self.dist_cache.delete(TYPE_NETWORK_POLICY, str(endpoint_id))
        if self.npds_pusher is not None:
            try:
                self.npds_pusher.remove(ep)
            except OSError:
                log.warning("NPDS prune failed; verdict service unreachable")
        ep.set_state(EndpointState.DISCONNECTED, "deleted")
        EndpointCount.set(len(self.endpoint_manager))
        # remove persisted state
        ep_dir = os.path.join(self._state_dir(), str(endpoint_id))
        cfg = os.path.join(ep_dir, "ep_config.json")
        if os.path.isfile(cfg):
            os.unlink(cfg)
            try:
                os.rmdir(ep_dir)
            except OSError:
                pass
        return True

    def endpoint_update_labels(
        self, endpoint_id: int, labels: list[str]
    ) -> bool:
        """Replace an endpoint's identity labels: reallocate the
        identity, resync the ipcache, and regenerate (reference:
        pkg/endpoint UpdateLabels/replaceIdentityLabels — the workload
        watcher's correlation path lands here)."""
        ep = self.endpoint_manager.lookup(endpoint_id)
        if ep is None:
            return False
        new = Labels.from_model(labels)
        if ep.labels == new:
            return True
        old_identity = ep.security_identity
        identity, _ = self._allocate_identity(new)
        ep.labels = new
        ep.set_identity(identity)
        if old_identity is not None:
            self._kvstore_publish(
                "identity release",
                lambda: self.identity_allocator.release(old_identity),
            )
        if ep.ipv4:
            self.ipcache.upsert(ep.ipv4, identity.id)
            self._kvstore_publish(
                "ipcache upsert",
                lambda: self.ipcache_sync.upsert_to_kvstore(
                    self._local_pair(ep.ipv4, identity.id)
                ),
            )
        ep.force_policy_compute = True
        ep.set_state(EndpointState.WAITING_TO_REGENERATE, "labels changed")
        self.build_queue.enqueue(ep, key=ep.id)
        return True

    def endpoint_regenerate(self, endpoint_id: int) -> bool:
        ep = self.endpoint_manager.lookup(endpoint_id)
        if ep is None:
            return False
        ep.force_policy_compute = True
        ep.set_state(EndpointState.WAITING_TO_REGENERATE, "api request")
        self.build_queue.enqueue(ep, key=ep.id)
        return True

    def restore_endpoints(self) -> int:
        """reference: daemon restoreOldEndpoints + regenerateRestored."""
        restored = Endpoint.restore_from_dir(self._state_dir())
        for ep in restored:
            if self.endpoint_manager.lookup(ep.id) is not None:
                continue
            self.endpoint_manager.insert(ep)
            if ep.security_identity is not None and ep.labels:
                # Re-allocate to re-register this node's reference.
                identity, _ = self._allocate_identity(
                    ep.security_identity.labels
                )
                ep.set_identity(identity)
            if ep.ipv4 and ep.security_identity is not None:
                self.ipcache.upsert(ep.ipv4, ep.security_identity.id)
            ep.set_state(EndpointState.WAITING_TO_REGENERATE, "restored")
            self.build_queue.enqueue(ep, key=ep.id)
        EndpointCount.set(len(self.endpoint_manager))
        return len(restored)

    def _state_dir(self) -> str:
        d = os.path.join(self.config.run_dir, self.config.state_dir)
        os.makedirs(d, exist_ok=True)
        return d

    # -- policy ------------------------------------------------------------

    def policy_add(self, rules: list[Rule]) -> int:
        """reference: daemon/policy.go:171 PolicyAdd."""
        for r in rules:
            try:
                r.sanitize()
            except Exception:
                PolicyImportErrors.inc()
                raise
        with self.policy.mutex:
            rev = self.policy.add_list(rules)
            prefixes = []
            for r in rules:
                prefixes.extend(r.get_cidr_prefixes())
        # Every policy CIDR prefix gets a local identity + ipcache entry
        # so the datapath can classify CIDR traffic (reference:
        # daemon/policy.go:201 ipcache.AllocateCIDRs).
        self._allocate_cidr_identities(prefixes)
        PolicyRevision.set(rev)
        PolicyCount.set(self.policy.num_rules())
        self.monitor.send_agent_notification(
            AGENT_NOTIFY_POLICY_UPDATED,
            f"policy updated to revision {rev} ({len(rules)} rules)",
            revision=rev,
        )
        self.trigger_policy_updates()
        return rev

    def _allocate_cidr_identities(self, prefixes: list[str]) -> None:
        """reference: pkg/ipcache AllocateCIDRs — allocate an identity
        carrying the cidr label per prefix and publish it to the ipcache."""
        from ..labels.cidr import ip_string_to_label

        for prefix in prefixes:
            lbl = ip_string_to_label(prefix)
            if lbl is None:
                continue
            lbls = Labels()
            lbls.upsert(lbl)
            ident, _ = self._allocate_identity(lbls)
            self._cidr_identities[prefix] = ident
            self.ipcache.upsert(prefix, ident.id)

    def _release_unused_cidr_identities(self) -> None:
        """Release CIDR identities no longer referenced by any rule
        (reference: daemon/policy.go removedPrefixes refcounting)."""
        live = set()
        for r in self.policy.rules:
            live.update(r.get_cidr_prefixes())
        for prefix in list(self._cidr_identities):
            if prefix not in live:
                ident = self._cidr_identities.pop(prefix)
                self.ipcache.delete(prefix)
                # Same degraded contract as endpoint releases: the
                # policy deletion already happened; a fenced store must
                # not abort it half-applied (the allocator's pending-
                # unref ledger retries the remote side via run_gc).
                self._kvstore_publish(
                    "cidr identity release",
                    lambda: self.identity_allocator.release(ident),
                )

    def policy_delete(self, labels: LabelArray) -> tuple[int, int]:
        """reference: daemon/policy.go PolicyDelete."""
        rev, deleted = self.policy.delete_by_labels(labels)
        if deleted:
            self._release_unused_cidr_identities()
            PolicyRevision.set(rev)
            PolicyCount.set(self.policy.num_rules())
            self.monitor.send_agent_notification(
                AGENT_NOTIFY_POLICY_UPDATED,
                f"policy revision {rev}: {deleted} rules deleted",
                revision=rev,
            )
            self.trigger_policy_updates()
        return rev, deleted

    def policy_get(self) -> str:
        return self.policy.get_json()

    def policy_trace(self, from_labels, to_labels, dports=None) -> tuple[str, str]:
        """reference: cilium policy trace / daemon trace API."""
        import io

        ctx = SearchContext(
            from_labels=from_labels, to_labels=to_labels, dports=dports or []
        )
        ctx.trace = Tracing.ENABLED
        ctx.logging = io.StringIO()
        verdict = self.policy.allows_ingress(ctx)
        return str(verdict), ctx.logging.getvalue()

    def trigger_policy_updates(self) -> None:
        self.policy_trigger.trigger()

    def _trigger_policy_updates_now(self) -> None:
        self.endpoint_manager.trigger_policy_updates(
            lambda ep: self.build_queue.enqueue(ep, key=ep.id)
        )

    # -- status ------------------------------------------------------------

    def _sync_metrics(self) -> None:
        EndpointCount.set(len(self.endpoint_manager))
        PolicyRevision.set(self.policy.get_revision())
        PolicyCount.set(self.policy.num_rules())

    def status(self) -> dict:
        """reference: daemon/status.go getStatus."""
        return {
            "cilium": {"state": "Ok", "uptime_s": round(
                time.time() - self._started, 1)},
            "kvstore": {
                "state": "Degraded" if self._kvstore_degraded else "Ok",
                "status": self.kvstore.status(),
                "degraded": self._kvstore_degraded,
                # Fencing epoch the client has observed (None for
                # local/file backends, which cannot fail over).
                "epoch": getattr(self.kvstore, "epoch", None),
                # Client-side failure counters (reference: kvstore
                # errors surfacing via controller failure counts).
                "counters": (
                    self.kvstore.counters.snapshot()
                    if hasattr(self.kvstore, "counters")
                    else {}
                ),
            },
            "node": self.node_name,
            "cluster": self.config.cluster_name,
            "policy": {
                "revision": self.policy.get_revision(),
                "rules": self.policy.num_rules(),
            },
            "endpoints": {
                "total": len(self.endpoint_manager),
                "by_state": self._endpoints_by_state(),
            },
            "identity": {
                "allocated": len(self.identity_allocator.get_identity_cache()),
            },
            "ipcache": {"entries": len(self.ipcache.dump())},
            "proxy": {
                "redirects": len(self.proxy_manager.redirects),
                "port_range": (
                    f"{self.config.proxy_port_min}-"
                    f"{self.config.proxy_port_max}"
                ),
            },
            "monitor": self.monitor.status(),
            "verdict_service": self._verdict_service_status(),
            "controllers": [
                {
                    "name": s.name,
                    "success": s.success_count,
                    "failure": s.failure_count,
                    "last_error": s.last_error,
                }
                for s in self.controllers.statuses()
            ],
        }

    def _verdict_service_status(self):
        """Counters from the attached verdict service (reference: the
        agent's Envoy admin scrape feeding `cilium status`)."""
        if self.npds_pusher is None:
            return None
        try:
            st = self.npds_pusher.client.status()
        except Exception:  # noqa: BLE001 — service may be down
            return {"state": "unreachable"}
        st["state"] = "Ok"
        st["npds_pushes"] = self.npds_pusher.pushes
        st["npds_nacks"] = self.npds_pusher.nacks
        return st

    def _endpoints_by_state(self) -> dict:
        out: dict[str, int] = {}
        for ep in self.endpoint_manager.get_endpoints():
            out[ep.state.value] = out.get(ep.state.value, 0) + 1
        return out

    def metrics_text(self) -> str:
        return metrics_registry.expose()

    # -- shutdown ----------------------------------------------------------

    def start_dns_poller(self, resolver, interval: float | None = None):
        """Start the ToFQDNs DNS poller with the given resolver
        (reference: fqdn.StartDNSPoller from daemon bootstrap)."""
        from ..fqdn import DnsPoller

        kwargs = {} if interval is None else {"interval": interval}
        self.dns_poller = DnsPoller(
            self.policy,
            resolver,
            on_change=self.trigger_policy_updates,
            controllers=self.controllers,
            **kwargs,
        ).start()
        return self.dns_poller

    def close(self) -> None:
        self.policy_trigger.shutdown()
        self.build_queue.stop()
        self.controllers.remove_all()
        self.ipcache_sync.stop()
        self.node_discovery.close()
        self.identity_allocator.close()
        if self.health_responder is not None:
            self.health_responder.close()
        if self.npds_pusher is not None:
            self.npds_pusher.close()
        if self.pprof_server is not None:
            self.pprof_server.shutdown()
            self.pprof_server.server_close()  # release the listening fd
        self.kvstore.close()


def _accesslog_event(rec):
    from ..monitor.monitor import MSG_TYPE_ACCESS_LOG, MonitorEvent

    proto = (
        "http" if rec.http else "kafka" if rec.kafka
        else (rec.l7.proto if rec.l7 else "?")
    )
    info = ""
    if rec.http:
        info = f"{rec.http.method} {rec.http.url} -> {rec.http.code}"
    elif rec.kafka:
        info = f"{rec.kafka.api_key} topics={rec.kafka.topics}"
    elif rec.l7:
        info = str(rec.l7.fields)
    return MonitorEvent(
        MSG_TYPE_ACCESS_LOG,
        {"verdict": rec.verdict, "l7_protocol": proto, "info": info},
    )
