"""Span timing statistics (reference: pkg/spanstat/spanstat.go:100).

Measures named stages of long operations (endpoint regeneration phases),
accumulating success/failure durations separately.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


class SpanStat:
    def __init__(self) -> None:
        self.success_duration = 0.0
        self.failure_duration = 0.0
        self.num_success = 0
        self.num_failure = 0
        self._start = 0.0

    def start(self) -> "SpanStat":
        self._start = time.monotonic()
        return self

    def end(self, success: bool = True) -> float:
        """Accumulate the elapsed span; returns its duration."""
        if self._start == 0.0:
            return 0.0
        d = time.monotonic() - self._start
        self._start = 0.0
        if success:
            self.success_duration += d
            self.num_success += 1
        else:
            self.failure_duration += d
            self.num_failure += 1
        return d

    def total(self) -> float:
        return self.success_duration + self.failure_duration

    def seconds(self) -> float:
        return self.total()


@dataclass
class SpanStats:
    """Named span collection for one operation (the shape of the
    reference's regeneration Statistics structs)."""

    spans: dict[str, SpanStat] = field(default_factory=dict)

    def span(self, name: str) -> SpanStat:
        return self.spans.setdefault(name, SpanStat())

    def report(self) -> dict[str, float]:
        return {name: s.total() for name, s in self.spans.items()}
