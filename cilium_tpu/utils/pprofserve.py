"""Opt-in profiling endpoint.

reference: pkg/pprof/pprof.go — ``Enable`` serves Go's net/http/pprof
on localhost:6060.  The Python analog serves the equivalent trio on a
localhost HTTP socket:

- ``/debug/pprof/profile?seconds=N`` — statistical profile of ALL live
  threads: ``sys._current_frames`` sampled every 5ms for N seconds,
  aggregated to sample counts per frame (Go's CPU profile is likewise
  a sampling profiler; a deterministic cProfile would only see the
  handler thread)
- ``/debug/pprof/threads``          — stack dump of every live thread
  (the goroutine-dump analog)
- ``/debug/pprof/heap``             — tracemalloc top allocations if
  tracing is active, else a gc generation/object summary
"""

from __future__ import annotations

import gc
import logging
import sys
import threading
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

log = logging.getLogger(__name__)

API_ADDRESS = ("127.0.0.1", 6060)  # reference: pprof.go apiAddress
SAMPLE_INTERVAL = 0.005


def profile_text(seconds: float = 1.0, top: int = 50) -> str:
    """Sample every live thread's current frame for ``seconds``."""
    me = threading.get_ident()
    counts: Counter = Counter()
    stop = threading.Event()
    n_samples = 0
    while not stop.wait(SAMPLE_INTERVAL):
        n_samples += 1
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            code = frame.f_code
            # co_qualname needs py3.11.  The fallback must keep the
            # enclosing function's name for <genexpr>/<lambda>/<listcomp>
            # frames (their co_name alone is anonymous, and a hot
            # comprehension would otherwise hide its owner from the
            # profile).
            qual = getattr(code, "co_qualname", None)
            if qual is None:
                qual = code.co_name
                if qual.startswith("<") and frame.f_back is not None:
                    qual = f"{frame.f_back.f_code.co_name}.{qual}"
            counts[
                f"{code.co_filename}:{frame.f_lineno} ({qual})"
            ] += 1
        if n_samples * SAMPLE_INTERVAL >= seconds:
            stop.set()
    lines = [f"samples: {n_samples} interval: {SAMPLE_INTERVAL * 1e3:.0f}ms"]
    for where, n in counts.most_common(top):
        lines.append(f"{n:8d} {where}")
    return "\n".join(lines) + "\n"


def threads_text() -> str:
    """Stack dump of all live threads (goroutine-dump analog)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def heap_text(top: int = 25) -> str:
    try:
        import tracemalloc

        if tracemalloc.is_tracing():
            snap = tracemalloc.take_snapshot()
            stats = snap.statistics("lineno")[:top]
            return "\n".join(str(s) for s in stats) + "\n"
    except ImportError:  # pragma: no cover
        pass
    counts = gc.get_count()
    return (
        f"gc counts: {counts}\n"
        f"tracked objects: {len(gc.get_objects())}\n"
        "(start tracemalloc for per-line allocations)\n"
    )


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        url = urlparse(self.path)
        if url.path == "/debug/pprof/profile":
            secs = float(parse_qs(url.query).get("seconds", ["1"])[0])
            body = profile_text(min(secs, 30.0))
        elif url.path == "/debug/pprof/threads":
            body = threads_text()
        elif url.path == "/debug/pprof/heap":
            body = heap_text()
        else:
            self.send_error(404)
            return
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


def enable(address: tuple[str, int] | None = None) -> ThreadingHTTPServer:
    """Start the profiling server in the background (reference:
    pprof.go Enable); returns the server so tests/callers can stop it.
    Port 0 picks a free port (server.server_address reports it)."""
    srv = ThreadingHTTPServer(address or API_ADDRESS, _Handler)
    t = threading.Thread(target=srv.serve_forever, name="pprof", daemon=True)
    t.start()
    log.info("pprof API served on %s:%d", *srv.server_address[:2])
    return srv
