"""Support infrastructure: controllers, triggers, completions, reverts,
span timing, backoff, metrics, logging, configuration.

The array-native framework's equivalent of the reference's pkg/{controller,
trigger,completion,revert,spanstat,backoff,metrics,logging,option,defaults}.
"""
