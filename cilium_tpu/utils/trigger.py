"""Serialized, rate-limited trigger (reference: pkg/trigger/trigger.go).

Folds bursts of Trigger() calls into serialized TriggerFunc invocations at
most once per min_interval — the mechanism behind batched policy
regeneration kicks.
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class Trigger:
    def __init__(
        self,
        trigger_func: Callable[[], None],
        min_interval: float = 0.0,
        sleep_interval: float = 0.01,
        name: str = "",
    ) -> None:
        self.trigger_func = trigger_func
        self.min_interval = min_interval
        self.sleep_interval = sleep_interval
        self.name = name
        self._pending = False
        self._mutex = threading.Lock()
        self._wake = threading.Event()
        self._closed = threading.Event()
        self.last_trigger = 0.0
        self.fold_count = 0  # triggers folded into the next invocation
        self.call_count = 0
        self._thread = threading.Thread(
            target=self._waiter, name=f"trigger-{name}", daemon=True
        )
        self._thread.start()

    def trigger(self) -> None:
        """Non-blocking request (reference: trigger.go:90)."""
        with self._mutex:
            self._pending = True
            self.fold_count += 1
        self._wake.set()

    def shutdown(self) -> None:
        self._closed.set()
        self._wake.set()

    def _needs_delay(self) -> tuple[bool, float]:
        if self.min_interval == 0:
            return False, 0.0
        remaining = self.last_trigger + self.min_interval - time.monotonic()
        return remaining > 0, remaining

    def _waiter(self) -> None:
        while not self._closed.is_set():
            with self._mutex:
                pending = self._pending
                self._pending = False
                folded = self.fold_count
                if pending:
                    self.fold_count = 0
            if pending:
                delay, remaining = self._needs_delay()
                while delay and not self._closed.is_set():
                    time.sleep(min(remaining, self.sleep_interval))
                    delay, remaining = self._needs_delay()
                if self._closed.is_set():
                    return
                self.last_trigger = time.monotonic()
                self.call_count += 1
                self.trigger_func()
            else:
                self._wake.wait(timeout=self.sleep_interval)
                self._wake.clear()
