"""Structured logging with per-subsystem fields.

reference: pkg/logging + pkg/logging/logfields — logrus-style structured
entries with a ``subsys`` field per package, runtime level flipping, and
optional hooks receiving every record (the logstash/fluentd seam).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any, Callable

_root = logging.getLogger("cilium_tpu")
_root.setLevel(logging.INFO)
_handler: logging.Handler | None = None
_hooks: list[Callable[[dict], None]] = []
_mutex = threading.Lock()

# Common field names (reference: pkg/logging/logfields/logfields.go).
ENDPOINT_ID = "endpointID"
IDENTITY = "identity"
POLICY_REVISION = "policyRevision"
L7_PROTOCOL = "l7Protocol"


class _StructuredFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        fields = getattr(record, "structured_fields", {})
        base = (
            f"{self.formatTime(record, '%Y-%m-%dT%H:%M:%S')} "
            f"{record.levelname.lower():7s} {record.getMessage()}"
        )
        if fields:
            extras = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
            return f"{base} {extras}"
        return base


def _ensure_handler() -> None:
    global _handler
    with _mutex:
        if _handler is None:
            _handler = logging.StreamHandler(sys.stderr)
            _handler.setFormatter(_StructuredFormatter())
            _root.addHandler(_handler)


class FieldLogger:
    """Logger carrying bound structured fields (logrus Entry analog)."""

    def __init__(self, fields: dict[str, Any] | None = None) -> None:
        self.fields = fields or {}

    def with_field(self, key: str, value: Any) -> "FieldLogger":
        return FieldLogger({**self.fields, key: value})

    def with_fields(self, **kwargs: Any) -> "FieldLogger":
        return FieldLogger({**self.fields, **kwargs})

    def _log(self, level: int, msg: str) -> None:
        _ensure_handler()
        record_fields = dict(self.fields)
        _root.log(level, msg, extra={"structured_fields": record_fields})
        entry = {
            "ts": time.time(),
            "level": logging.getLevelName(level).lower(),
            "msg": msg,
            **record_fields,
        }
        for hook in list(_hooks):
            try:
                hook(entry)
            except Exception:  # noqa: BLE001 — hooks never break logging
                pass

    def debug(self, msg: str) -> None:
        self._log(logging.DEBUG, msg)

    def info(self, msg: str) -> None:
        self._log(logging.INFO, msg)

    def warning(self, msg: str) -> None:
        self._log(logging.WARNING, msg)

    def error(self, msg: str) -> None:
        self._log(logging.ERROR, msg)

    def to_json(self) -> str:
        return json.dumps(self.fields)


default_logger = FieldLogger()


def get_logger(subsys: str) -> FieldLogger:
    """Per-subsystem logger (reference: logfields.LogSubsys)."""
    return default_logger.with_field("subsys", subsys)


def set_log_level(level: str) -> None:
    """Runtime level flip (reference: logging.SetLogLevel)."""
    _root.setLevel(getattr(logging, level.upper()))


def add_hook(hook: Callable[[dict], None]) -> None:
    """Register a hook receiving every structured record
    (reference: logging hooks / logstash export)."""
    _hooks.append(hook)


def remove_hook(hook: Callable[[dict], None]) -> None:
    try:
        _hooks.remove(hook)
    except ValueError:
        pass
