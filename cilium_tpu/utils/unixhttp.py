"""Shared unix-socket HTTP server plumbing.

One threading unix-stream HTTP server used by every plugin-style
surface (the agent REST API, the docker libnetwork driver) so socket
lifecycle fixes land once: stale-socket unlink, directory creation,
daemonized serve thread, shutdown + unlink on close.
"""

from __future__ import annotations

import os
import socketserver
import threading


class UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


def serve_unix(path: str, handler_cls) -> UnixHTTPServer:
    """Bind ``handler_cls`` on a fresh unix socket at ``path`` and serve
    it from a daemon thread; returns the server (close with
    ``shutdown_unix``)."""
    if os.path.exists(path):
        os.unlink(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    server = UnixHTTPServer(path, handler_cls)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def shutdown_unix(server: UnixHTTPServer, path: str) -> None:
    server.shutdown()
    server.server_close()
    try:
        os.unlink(path)
    except OSError:
        pass
