"""Completion tracking with deadlines.

reference: pkg/completion — policy application blocks on proxy ACKs via a
WaitGroup of Completions with a context deadline (pkg/endpoint/bpf.go:555,
pkg/envoy/xds/ack.go).
"""

from __future__ import annotations

import threading


class CompletionError(TimeoutError):
    pass


class Completion:
    """One pending acknowledgement (reference: completion/completion.go)."""

    def __init__(self, wg: "WaitGroup | None" = None) -> None:
        self._event = threading.Event()
        self._wg = wg
        if wg is not None:
            wg._add(self)

    def complete(self) -> None:
        self._event.set()

    @property
    def completed(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)


class WaitGroup:
    """Waits for all added completions (reference: completion.WaitGroup)."""

    def __init__(self, timeout: float | None = None) -> None:
        self.timeout = timeout
        self._completions: list[Completion] = []
        self._mutex = threading.Lock()

    def _add(self, c: Completion) -> None:
        with self._mutex:
            self._completions.append(c)

    def add_completion(self) -> Completion:
        return Completion(self)

    def wait(self, timeout: float | None = None) -> None:
        """Blocks until all complete; raises CompletionError on deadline."""
        import time

        deadline = None
        t = timeout if timeout is not None else self.timeout
        if t is not None:
            deadline = time.monotonic() + t
        with self._mutex:
            pending = list(self._completions)
        for c in pending:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise CompletionError("completion wait deadline exceeded")
            if not c.wait(remaining):
                raise CompletionError("completion wait deadline exceeded")
