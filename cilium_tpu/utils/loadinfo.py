"""System-load logging during long-running operations.

reference: pkg/loadinfo/loadinfo.go — LogCurrentSystemLoad logs load
averages, memory, and any process above a CPU watermark;
LogPeriodicSystemLoad repeats that every interval until stopped (the
daemon wraps long compiles/regenerations with it).  This build reads
/proc directly instead of gopsutil; on non-Linux the probes degrade to
empty results rather than failing.
"""

from __future__ import annotations

import logging
import os
import threading
import time

log = logging.getLogger(__name__)

BACKGROUND_INTERVAL = 5.0  # reference: loadinfo.go backgroundInterval
CPU_WATERMARK = 1.0  # reference: loadinfo.go cpuWatermark (percent)


def _load_avg() -> tuple[float, float, float] | None:
    try:
        with open("/proc/loadavg") as f:
            p = f.read().split()
        return float(p[0]), float(p[1]), float(p[2])
    except (OSError, ValueError, IndexError):
        return None


def _mem_info() -> dict | None:
    try:
        fields = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                fields[k] = int(rest.split()[0])  # kB
        total = fields["MemTotal"]
        avail = fields.get("MemAvailable", fields.get("MemFree", 0))
        used = total - avail
        return {
            "total_mb": total // 1024,
            "used_mb": used // 1024,
            "available_mb": avail // 1024,
            "used_pct": round(100.0 * used / total, 2) if total else 0.0,
        }
    except (OSError, ValueError, KeyError):
        return None


class _ProcSampler:
    """Per-process CPU%% between consecutive samples (utime+stime delta
    over wall delta), mirroring the reference's process listing."""

    def __init__(self) -> None:
        self._prev: dict[int, tuple[float, float]] = {}
        self._tick = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100

    def sample(self) -> list[tuple[int, str, float]]:
        now = time.monotonic()
        out = []
        try:
            pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
        except OSError:
            return out
        fresh: dict[int, tuple[float, float]] = {}
        for pid in pids:
            try:
                with open(f"/proc/{pid}/stat") as f:
                    raw = f.read()
                # comm may contain spaces; it is parenthesised.
                rpar = raw.rindex(")")
                comm = raw[raw.index("(") + 1 : rpar]
                rest = raw[rpar + 2 :].split()
                cpu_s = (int(rest[11]) + int(rest[12])) / self._tick
            except (OSError, ValueError, IndexError):
                continue
            fresh[pid] = (cpu_s, now)
            prev = self._prev.get(pid)
            if prev is not None and now > prev[1]:
                pct = 100.0 * (cpu_s - prev[0]) / (now - prev[1])
                if pct >= CPU_WATERMARK:
                    out.append((pid, comm, round(pct, 2)))
        self._prev = fresh
        out.sort(key=lambda r: -r[2])
        return out


def log_current_system_load(log_func=log.info, sampler: _ProcSampler | None = None):
    """One snapshot: load averages + memory + busy processes
    (reference: loadinfo.go LogCurrentSystemLoad)."""
    la = _load_avg()
    if la is not None:
        log_func("Load 1-min: %.2f 5-min: %.2f 15min: %.2f", *la)
    mi = _mem_info()
    if mi is not None:
        log_func(
            "Memory: Total: %d Used: %d (%.2f%%) Available: %d",
            mi["total_mb"], mi["used_mb"], mi["used_pct"], mi["available_mb"],
        )
    for pid, comm, pct in (sampler or _ProcSampler()).sample():
        log_func("NAME %s PID %d CPU: %.2f%%", comm, pid, pct)
    return {"load": la, "memory": mi}


class PeriodicLoadLogger:
    """reference: loadinfo.go LogPeriodicSystemLoad — context manager
    logging system load every interval while a long operation runs."""

    def __init__(self, log_func=log.info, interval: float = BACKGROUND_INTERVAL):
        self.log_func = log_func
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sampler = _ProcSampler()

    def __enter__(self) -> "PeriodicLoadLogger":
        log_current_system_load(self.log_func, self._sampler)
        self._thread = threading.Thread(
            target=self._loop, name="loadinfo", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            log_current_system_load(self.log_func, self._sampler)

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
