"""Per-flow debug logging gate.

reference: pkg/flowdebug/flowdebug.go — a process-global switch; all
per-request/per-connection debug logging must route through here so the
(hot) per-flow paths pay a single boolean check when disabled.
"""

from __future__ import annotations

import logging

_per_flow_debug = False


def enable() -> None:
    global _per_flow_debug
    _per_flow_debug = True


def disable() -> None:
    global _per_flow_debug
    _per_flow_debug = False


def enabled() -> bool:
    return _per_flow_debug


def log(logger: logging.Logger, msg: str, *args) -> None:
    """Log a per-flow debug message only when enabled (reference:
    flowdebug.go Log/Logf)."""
    if _per_flow_debug:
        logger.debug(msg, *args)
