"""Revert stacks: roll back partial multi-step operations.

reference: pkg/revert — endpoint regeneration pushes a revert function per
completed step; on failure the stack runs in reverse (pkg/endpoint/
bpf.go:561-584).
"""

from __future__ import annotations

from typing import Callable


class RevertStack:
    """reference: revert/revert.go RevertStack."""

    def __init__(self) -> None:
        self._funcs: list[Callable[[], None]] = []

    def push(self, revert_func: Callable[[], None]) -> None:
        self._funcs.append(revert_func)

    def revert(self) -> None:
        """Run in reverse order; the first failure aborts (matching the
        reference's error-on-first-failure)."""
        while self._funcs:
            f = self._funcs.pop()
            f()

    def __len__(self) -> int:
        return len(self._funcs)


class FinalizeList:
    """Functions to run on success (reference: revert.FinalizeList)."""

    def __init__(self) -> None:
        self._funcs: list[Callable[[], None]] = []

    def append(self, f: Callable[[], None]) -> None:
        self._funcs.append(f)

    def finalize(self) -> None:
        for f in self._funcs:
            f()
        self._funcs.clear()
