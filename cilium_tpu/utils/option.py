"""Daemon configuration and runtime-mutable options.

reference: pkg/option — a typed config snapshot (config.go:168
daemonConfig) populated from flags, plus a runtime-mutable option map with
per-option verify/parse and change hooks (option.go), overlayable
per-endpoint (endpoint.go).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from . import defaults

# Boolean runtime options (reference: pkg/option/option.go option lib).
OPTION_DEBUG = "Debug"
OPTION_DROP_NOTIFY = "DropNotification"
OPTION_TRACE_NOTIFY = "TraceNotification"
OPTION_POLICY_VERDICT_NOTIFY = "PolicyVerdictNotification"
OPTION_CONNTRACK = "Conntrack"
OPTION_POLICY_ENABLED = "Policy"


@dataclass
class OptionSpec:
    name: str
    description: str = ""
    immutable: bool = False
    # parse raw string -> canonical value; default accepts true/false
    parse: Optional[Callable[[str], Any]] = None


def _parse_bool(v: str) -> bool:
    s = str(v).lower()
    if s in ("true", "enabled", "on", "1"):
        return True
    if s in ("false", "disabled", "off", "0"):
        return False
    raise ValueError(f"invalid option value {v!r}")


AVAILABLE_OPTIONS: dict[str, OptionSpec] = {
    OPTION_DEBUG: OptionSpec(OPTION_DEBUG, "Enable debugging"),
    OPTION_DROP_NOTIFY: OptionSpec(OPTION_DROP_NOTIFY, "Drop notifications"),
    OPTION_TRACE_NOTIFY: OptionSpec(OPTION_TRACE_NOTIFY, "Trace notifications"),
    OPTION_POLICY_VERDICT_NOTIFY: OptionSpec(
        OPTION_POLICY_VERDICT_NOTIFY, "Policy verdict notifications"
    ),
    OPTION_CONNTRACK: OptionSpec(OPTION_CONNTRACK, "Connection tracking"),
    OPTION_POLICY_ENABLED: OptionSpec(OPTION_POLICY_ENABLED, "Policy enforcement"),
}


class OptionMap:
    """Mutable option set with change hooks (reference: option.go
    BoolOptions + changedOption at daemon/daemon.go:1440)."""

    def __init__(self, parent: "OptionMap | None" = None) -> None:
        self._values: dict[str, bool] = {}
        self._parent = parent
        self._hooks: list[Callable[[str, bool], None]] = []
        self._mutex = threading.RLock()

    def get(self, name: str) -> bool:
        with self._mutex:
            if name in self._values:
                return self._values[name]
        if self._parent is not None:
            return self._parent.get(name)
        return False

    def set(self, name: str, value) -> bool:
        """Set; returns True if the effective value changed."""
        spec = AVAILABLE_OPTIONS.get(name)
        if spec is None:
            raise KeyError(f"unknown option {name!r}")
        if spec.immutable:
            raise PermissionError(f"option {name!r} is immutable")
        parse = spec.parse or _parse_bool
        v = parse(value) if isinstance(value, str) else bool(value)
        with self._mutex:
            old = self.get(name)
            self._values[name] = v
            changed = old != v
            hooks = list(self._hooks)
        if changed:
            for h in hooks:
                h(name, v)
        return changed

    def delete(self, name: str) -> None:
        """Remove the local override (per-endpoint overlay semantics)."""
        with self._mutex:
            self._values.pop(name, None)

    def add_change_hook(self, hook: Callable[[str, bool], None]) -> None:
        self._hooks.append(hook)

    def snapshot(self) -> dict[str, bool]:
        with self._mutex:
            out = dict(self._parent.snapshot()) if self._parent else {}
            out.update(self._values)
            return out


@dataclass
class DaemonConfig:
    """Typed config snapshot (reference: pkg/option/config.go:168)."""

    # Paths
    run_dir: str = defaults.RUNTIME_PATH
    state_dir: str = defaults.STATE_DIR
    socket_path: str = defaults.SOCK_PATH
    monitor_socket_path: str = defaults.MONITOR_SOCK_PATH
    access_log_path: str = ""

    # Cluster
    cluster_name: str = defaults.CLUSTER_NAME
    cluster_id: int = 0

    # Policy
    enable_policy: str = "default"  # default | always | never
    allow_localhost: str = "auto"  # auto | always | policy
    host_allows_world: bool = False

    # Proxy
    proxy_port_min: int = defaults.PROXY_PORT_MIN
    proxy_port_max: int = defaults.PROXY_PORT_MAX
    # How long a regeneration blocks waiting for the verdict service to
    # ACK an NPDS policy push before failing and reverting (reference:
    # the completion.WaitGroup context deadline at pkg/endpoint/bpf.go:555).
    proxy_ack_timeout_s: float = 5.0
    # This node's underlay IPv4 (the VXLAN tunnel endpoint peers encap
    # to).  Published as HostIP/TunnelEndpoint with every local
    # endpoint's ipcache pair (reference: pkg/ipcache/kvstore.go
    # marshals hostIP; bpf/lib/encap.h uses the learned tunnel endpoint).
    node_ipv4: str = ""

    # Device batching (TPU runtime)
    batch_flows: int = defaults.BATCH_FLOWS
    batch_width: int = defaults.BATCH_WIDTH
    batch_timeout_ms: float = defaults.BATCH_TIMEOUT_MS
    # Device dispatch: 'eager' pipelines per-op async dispatch (wins on
    # high-latency device links), 'jit' compiles one executable launch
    # per batch (wins co-located), 'auto' measures both at prewarm and
    # keeps the faster.
    dispatch_mode: str = "auto"  # auto | eager | jit
    # 'cpu' routes verdict models to the host CPU backend (removes the
    # device-link term; used by the co-located latency proof).
    verdict_device: str = "default"  # default | cpu
    # DIAGNOSTIC: replace verdict compute with a trivial all-allow
    # device op so the sidecar seam itself (batch fill -> wire ->
    # dispatch -> device call -> readback -> wire back) can be measured
    # with the verdict-compute term removed.  Never a production config.
    seam_probe: bool = False

    # Overload & fault containment (sidecar verdict path).  The
    # contract is bounded-latency degradation, never availability loss:
    # a stuck device call quarantines the device (verdicts continue
    # through the bit-identical host/oracle fallback), and a burst past
    # capacity sheds with a typed SHED verdict instead of queueing
    # unboundedly or hanging the caller.
    #
    # Upper bound on one device round (model call / readback) before
    # the watchdog deposes the dispatch worker and quarantines the
    # device.  Must comfortably exceed worst-case XLA compile times on
    # the deployment's device link; 0 disables the watchdog.
    device_call_timeout_s: float = 10.0
    # While quarantined, how often traffic re-probes the device for
    # automatic un-quarantine.
    device_reprobe_interval_s: float = 1.0
    # Consecutive crashed dispatch rounds before the device/engine is
    # treated as poisoned and quarantined (0 disables).
    device_fail_threshold: int = 3
    # Admission-queue watermarks: pending entries beyond this are shed
    # at submit (0 = unbounded), and queued entries older than this are
    # shed at dispatch (0 = no age bound).  Entries may also carry an
    # explicit per-entry deadline from the shim (wire DATA_BATCH_DL),
    # which takes precedence over the age watermark.
    shed_queue_entries: int = 1 << 17
    shed_queue_age_ms: float = 5000.0
    # Per-flow retained-bytes cap (engine flow buffers, the columnar
    # reassembly arena, and the service's oracle buffer mirror): a flow
    # that buffers more than this without a frame boundary gets a typed
    # protocol-error DROP and is closed, matching the reference's
    # bounded retained-data contract.
    max_flow_buffer: int = 1 << 20
    # Columnar reassembly lane (sidecar/reasm.py): serve the CRLF slow
    # lane with array passes per ROUND instead of feed/settle Python
    # per ENTRY.  Pipelined (batch_timeout_ms > 0) services only —
    # greedy rounds are 1-2 small messages and the columnar fixed cost
    # loses.  False keeps every round on the scalar engine/oracle rung.
    reasm: bool = True
    # Rounds with fewer lane-eligible entries than this fall back to
    # the scalar path (below it the per-round numpy fixed cost exceeds
    # the per-entry Python it replaces).
    reasm_min_entries: int = 4
    # Initial byte-arena capacity (grows geometrically; per-conn totals
    # stay bounded by max_flow_buffer regardless).
    reasm_arena_bytes: int = 1 << 20
    # Shared-memory transport (sidecar/shm.py): whether the service
    # accepts MSG_SHM_ATTACH ring negotiation.  False rejects attaches
    # typed — every session serves on the socket rung (the client's
    # transport preference degrades, it never fails).  Ring geometry is
    # client-owned (SidecarClient shm_* kwargs): the shim creates the
    # segments and the service only maps what was negotiated.
    shm_transport: bool = True
    # Ring-segment lease (seconds): how long the service waits after a
    # session dies WITHOUT MSG_SHM_DETACH before unlinking its shared-
    # memory segments.  The creator (shim) owns the unlink on every
    # orderly path; after an abrupt shim death this lease is the only
    # thing standing between the node and a /dev/shm leak per crash.
    shm_lease_s: float = 30.0
    # Verdict-ring oversize spree: this many CONSECUTIVE oversize
    # fallbacks demote the session's shm rung typed (oversize_spree) —
    # a session whose every frame misses the ring pays the fit check
    # for nothing.  The same threshold drives the client-side data-ring
    # spree.  0 disables.
    shm_oversize_spree: int = 32

    # Multi-tenant fan-in (N shim sessions, one dispatcher).  Deficit-
    # round-robin credit windows: a session may hold at most
    # max(shed_queue_entries / (sessions + 1), session_share_min)
    # OUTSTANDING entries (submitted and not yet answered — the window
    # covers the dispatcher queue AND the issued-not-answered
    # completion pipeline); excess submissions are shed typed
    # `session_quota` for THAT session only.  Credits return as
    # answers are written, so a flood's buffering lands on the
    # flooder while a session under its share is never refused.
    session_share_min: int = 64
    # Flood containment: this many over-quota sheds inside the strike
    # window escalate to a session quarantine (typed `flood`) for
    # session_quarantine_s — the flooding pod's data plane is answered
    # typed-SHED immediately instead of being classified per batch.
    # 0 disables escalation.
    session_flood_strikes: int = 200
    session_strike_window_s: float = 2.0
    session_quarantine_s: float = 5.0
    # Crash-loop containment: a shim identity that reconnects more
    # than this many times inside the reconnect window starts its next
    # session QUARANTINED (typed `reconnect_storm`) for
    # session_quarantine_s — control plane (replay) still serves, so a
    # healed pod exits the latch by just staying up.  0 disables.
    session_reconnect_storm: int = 8
    session_reconnect_window_s: float = 10.0

    # Multi-chip sharded verdict serving (parallel/rulesharding.py).
    # 'auto' builds a (flows, rules) device mesh at first engine bind
    # when the backend has more than one REAL accelerator device
    # (never on the CPU backend — virtual CPU devices share the same
    # host cores and a collective only adds overhead); 'on' forces the
    # mesh at any device count (how the CPU-mesh tests and smoke
    # benches run); 'off' keeps the single-chip executables.
    mesh: str = "auto"  # auto | on | off
    # RULE_AXIS extent: rule tables split-balanced and padded across
    # this many shards (HBM capacity for 100k+-rule tables; per-shard
    # NFA delta shrinks ~quadratically).  0 = 1 (no rule sharding).
    mesh_rule_shards: int = 0
    # FLOW_AXIS extent: batch axes shard across this many devices for
    # throughput.  0 = devices // rule_shards, floored to a power of
    # two (so every power-of-two dispatch bucket divides it) and
    # capped at the smallest bucket.  An EXPLICIT value may exceed the
    # smallest dispatch bucket (ROADMAP 5b): the service grows its
    # minimum bucket to the flow extent so >32-device pods shard the
    # flow axis fully.
    mesh_flow_shards: int = 0
    # Width-ladder reshape: after a partial device loss the policy
    # builder thread rebuilds the sharded wrappers over the surviving
    # devices at the next bucketable width (fallback covers only the
    # rebuild window).  False keeps the binary pre-PR-17 ladder:
    # any mesh fault demotes straight to the single-chip fallback.
    mesh_reshape: bool = True
    # Guarded mesh re-promotion: after a mesh demotion, the policy
    # builder thread re-probes the mesh off-path at most once per this
    # interval (rebuild one sharded executable, parity-probe it against
    # the single-chip fallback, re-promote typed on success).  0 keeps
    # the pre-PR-12 behavior: demotion sticky until restart.
    mesh_reprobe_interval_s: float = 5.0

    # Established-flow verdict cache (sidecar/service.py + client.py +
    # policy/invariance.py): per-flow decisions keyed (conn, direction,
    # policy epoch) that short-circuit byte-invariant flows — in the
    # shim before bytes cross the transport, and in the sidecar's
    # vectorized eligibility mask before any device round.  OFF by
    # default: the cache coalesces per-frame ops into stream-level
    # PASS ops (byte-equivalent forwarded output, not op-identical),
    # so the strict op-parity suites run against the true baseline;
    # every short-circuit site is gated on this knob (like
    # flow_observe).
    flow_cache: bool = False
    # Cap on service-side armed cache rows (beyond it, new flows stop
    # arming but existing rows keep serving).
    flow_cache_entries: int = 1 << 20

    # Hitless restart (sidecar/service.py handoff).  A starting
    # service that finds a live predecessor on its socket path pulls a
    # state handoff over the side channel (MSG_HANDOFF) before binding:
    # sessions, conns, grants, policy epoch and flow-buffer residue
    # carry over, and the predecessor is fenced (its late writes are
    # rejected typed).  False boots cold unconditionally — the crash-
    # restart path, which is always correct, just not warm.
    restart_handoff: bool = True
    # Bound on the whole handoff pull: the predecessor's quiesce
    # (in-flight rounds answered by the OLD process) and the snapshot
    # reply must land within this window, else the successor cold-
    # boots.  Also the successor's dial/read socket timeout.
    handoff_deadline_s: float = 5.0

    # Policy churn (sidecar/service.py epoch swap).  How long a
    # MSG_POLICY_UPDATE handler waits for the builder thread's staged
    # compile-then-swap to commit before acking UNKNOWN_ERROR (the
    # build keeps running and swaps when done; the old epoch serves
    # throughout).  Must comfortably exceed worst-case XLA compile
    # times on the deployment's device link.
    policy_swap_timeout_s: float = 120.0
    # Re-assert device-model vs host-oracle bit-identity on every new
    # epoch before it is committed (a small deterministic probe batch
    # per rebuilt engine; a mismatch fails the swap typed and the old
    # epoch keeps serving).
    policy_epoch_parity: bool = True

    # Verdict-path latency decomposition (sidecar/trace.py).
    # Always-on per-round stage histograms + occupancy/busy gauges
    # (False removes the metric observes; the bench's instrumentation-
    # disabled baseline — stamps themselves are ~ns and stay on).
    trace_stage_metrics: bool = True
    # 1-in-N per-entry span sampling into the trace ring (0 disables
    # sampling; slow exemplars are captured regardless).
    trace_sample_every: int = 4096
    # End-to-end latency above which a wire batch becomes a slow
    # exemplar (monitor event + accesslog annotation + ring).  0 makes
    # EVERY batch an exemplar — the e2e-test/forensics setting.
    trace_slow_ms: float = 50.0
    # Span ring capacity (bounded; oldest spans are evicted).
    trace_ring: int = 512

    # Flight recorder (sidecar/blackbox.py).  Always-on incident
    # timeline: every mediated typestate transition + overload markers
    # land in a bounded ring; fail-closed edges trigger automatic
    # postmortem bundles.  timeline_ring is the event ring capacity.
    timeline_ring: int = 512
    # Directory postmortem bundles are serialized to as JSON files
    # ("" keeps bundles in-memory only — they still ride the monitor
    # stream and the MSG_TIMELINE reply).
    timeline_bundle_dir: str = ""
    # True drops routine declared-silent edges (outcome None, not
    # fail-closed) from the ring — the low-noise setting; fail-closed
    # edges and counted transitions are always recorded.
    timeline_slow_only: bool = False

    # Flow-level verdict observability (flowlog/): per-flow records
    # with device-side rule attribution, populated per ROUND from all
    # decision layers and queryable via `cilium observe`/MSG_OBSERVE.
    # False removes record emission AND the attributed device call —
    # the flow_observe_overhead bench's disabled baseline.
    flow_observe: bool = True
    # Flow-record ring capacity in RECORDS (oldest rounds evicted whole).
    flowlog_ring: int = 8192

    # Modes
    dry_mode: bool = False  # reference: DryMode, pkg/endpoint/bpf.go:510
    restore_state: bool = True
    enable_health: bool = True  # reference: cilium-health launch
    pprof: bool = False  # reference: --pprof -> pkg/pprof Enable
    pprof_port: int = 6060  # reference: pprof.go apiAddress (0 = ephemeral)
    per_flow_debug: bool = False  # reference: pkg/flowdebug

    # kvstore
    kvstore: str = "local"  # local | file | tcp
    kvstore_opts: dict = field(default_factory=dict)

    # Monitor
    monitor_queue_size: int = defaults.MONITOR_QUEUE_SIZE

    # Runtime options
    opts: OptionMap = field(default_factory=OptionMap)

    def always_allow_localhost(self) -> bool:
        """reference: config.go AlwaysAllowLocalhost."""
        return self.allow_localhost == "always"

    def validate(self) -> None:
        """reference: config.go:338 Validate."""
        if self.enable_policy not in ("default", "always", "never"):
            raise ValueError(f"invalid enable_policy {self.enable_policy!r}")
        if not 0 < self.proxy_port_min < self.proxy_port_max <= 65535:
            raise ValueError("invalid proxy port range")
        if self.batch_flows <= 0 or self.batch_width <= 0:
            raise ValueError("batch dimensions must be positive")
        if self.dispatch_mode not in ("auto", "eager", "jit"):
            raise ValueError(f"invalid dispatch_mode {self.dispatch_mode!r}")
        if self.verdict_device not in ("default", "cpu"):
            raise ValueError(f"invalid verdict_device {self.verdict_device!r}")
        if self.cluster_id < 0 or self.cluster_id > 255:
            raise ValueError("cluster-id must be in [0, 255]")
        if (
            self.device_call_timeout_s < 0
            or self.device_reprobe_interval_s < 0
            or self.device_fail_threshold < 0
            or self.shed_queue_entries < 0
            or self.shed_queue_age_ms < 0
            or self.max_flow_buffer < 0
        ):
            raise ValueError("containment thresholds must be non-negative")
        if (
            self.session_share_min < 0
            or self.session_flood_strikes < 0
            or self.session_strike_window_s < 0
            or self.session_quarantine_s < 0
            or self.session_reconnect_storm < 0
            or self.session_reconnect_window_s < 0
            or self.shm_lease_s < 0
            or self.shm_oversize_spree < 0
        ):
            raise ValueError(
                "session fairness/containment thresholds must be "
                "non-negative"
            )
        if (
            self.trace_sample_every < 0
            or self.trace_slow_ms < 0
            or self.trace_ring <= 0
        ):
            raise ValueError(
                "trace knobs must be non-negative (ring positive)"
            )
        if self.flowlog_ring <= 0:
            raise ValueError("flowlog_ring must be positive")
        if self.timeline_ring <= 0:
            raise ValueError("timeline_ring must be positive")
        if self.mesh not in ("auto", "on", "off"):
            raise ValueError(f"invalid mesh {self.mesh!r}")
        if self.mesh_rule_shards < 0 or self.mesh_flow_shards < 0:
            raise ValueError("mesh shard counts must be non-negative")
        if self.mesh_reprobe_interval_s < 0:
            raise ValueError("mesh_reprobe_interval_s must be >= 0")
        if self.flow_cache_entries < 0:
            raise ValueError("flow_cache_entries must be >= 0")
        if self.handoff_deadline_s < 0:
            raise ValueError("handoff_deadline_s must be >= 0")


# Global config (reference: option.Config singleton).
config = DaemonConfig()
