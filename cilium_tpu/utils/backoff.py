"""Exponential backoff with jitter (reference: pkg/backoff/backoff.go).

Used by kvstore reconnects and distribution clients.
"""

from __future__ import annotations

import random
import time


class Exponential:
    def __init__(
        self,
        min_duration: float = 1.0,
        max_duration: float = 0.0,  # 0 = unbounded
        factor: float = 2.0,
        jitter: bool = True,
        name: str = "",
    ) -> None:
        self.min = min_duration
        self.max = max_duration
        self.factor = factor
        self.jitter = jitter
        self.name = name
        self.attempt = 0

    def duration(self, attempt: int | None = None) -> float:
        """Backoff duration for the given (1-based) attempt."""
        if attempt is None:
            self.attempt += 1
            attempt = self.attempt
        d = self.min * (self.factor ** (attempt - 1))
        if self.max and d > self.max:
            d = self.max
        if self.jitter:
            d = d / 2 + random.random() * d / 2
        return d

    def reset(self) -> None:
        self.attempt = 0

    def wait(self) -> None:
        time.sleep(self.duration())
