"""Metrics registry with Prometheus text exposition.

reference: pkg/metrics/metrics.go:51-430 — counters/gauges/histograms for
endpoint counts, regeneration times, policy revision, drop/forward counts,
proxy redirects; exported in Prometheus text format.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Iterable

NAMESPACE = "cilium_tpu"


def _fmt_labels(label_names, label_values) -> str:
    if not label_names:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in zip(label_names, label_values)
    )
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple = ()) -> None:
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}
        self._mutex = threading.Lock()

    def inc(self, *label_values, amount: float = 1.0) -> None:
        with self._mutex:
            self._values[label_values] = self._values.get(label_values, 0.0) + amount

    def get(self, *label_values) -> float:
        return self._values.get(label_values, 0.0)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        if not self._values and not self.label_names:
            yield f"{self.name} 0"
        for lv, v in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(self.label_names, lv)} {v:g}"


class Gauge:
    def __init__(self, name: str, help_: str, label_names: tuple = ()) -> None:
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}
        self._mutex = threading.Lock()

    def set(self, value: float, *label_values) -> None:
        with self._mutex:
            self._values[label_values] = value

    def inc(self, *label_values, amount: float = 1.0) -> None:
        with self._mutex:
            self._values[label_values] = self._values.get(label_values, 0.0) + amount

    def dec(self, *label_values) -> None:
        self.inc(*label_values, amount=-1.0)

    def get(self, *label_values) -> float:
        return self._values.get(label_values, 0.0)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        if not self._values and not self.label_names:
            yield f"{self.name} 0"
        for lv, v in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(self.label_names, lv)} {v:g}"


DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10
)


class Histogram:
    def __init__(
        self, name: str, help_: str, label_names: tuple = (),
        buckets: tuple = DEFAULT_BUCKETS,
    ) -> None:
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}
        self._mutex = threading.Lock()

    def observe(self, value: float, *label_values) -> None:
        with self._mutex:
            counts = self._counts.setdefault(
                label_values, [0] * len(self.buckets)
            )
            # Cumulative buckets: value counts into every bucket with
            # bound >= value (le is inclusive).
            for j in range(bisect_left(self.buckets, value), len(self.buckets)):
                counts[j] += 1
            self._sums[label_values] = self._sums.get(label_values, 0.0) + value
            self._totals[label_values] = self._totals.get(label_values, 0) + 1

    def get_count(self, *label_values) -> int:
        return self._totals.get(label_values, 0)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        for lv in sorted(self._totals):
            counts = self._counts[lv]
            for j, b in enumerate(self.buckets):
                labels = _fmt_labels(
                    self.label_names + ("le",), lv + (f"{b:g}",)
                )
                yield f"{self.name}_bucket{labels} {counts[j]}"
            labels_inf = _fmt_labels(self.label_names + ("le",), lv + ("+Inf",))
            yield f"{self.name}_bucket{labels_inf} {self._totals[lv]}"
            yield (
                f"{self.name}_sum{_fmt_labels(self.label_names, lv)} "
                f"{self._sums[lv]:g}"
            )
            yield (
                f"{self.name}_count{_fmt_labels(self.label_names, lv)} "
                f"{self._totals[lv]}"
            )


class Registry:
    def __init__(self) -> None:
        self._collectors: list = []
        self._mutex = threading.Lock()

    def register(self, collector):
        with self._mutex:
            self._collectors.append(collector)
        return collector

    def counter(self, name, help_, label_names=()):
        return self.register(Counter(f"{NAMESPACE}_{name}", help_, label_names))

    def gauge(self, name, help_, label_names=()):
        return self.register(Gauge(f"{NAMESPACE}_{name}", help_, label_names))

    def histogram(self, name, help_, label_names=(), buckets=DEFAULT_BUCKETS):
        return self.register(
            Histogram(f"{NAMESPACE}_{name}", help_, label_names, buckets)
        )

    def expose(self) -> str:
        """Prometheus text format."""
        lines: list[str] = []
        with self._mutex:
            collectors = list(self._collectors)
        for c in collectors:
            lines.extend(c.collect())
        return "\n".join(lines) + "\n"


# Global registry + the reference's core metric set
# (reference: pkg/metrics/metrics.go:51-430).
registry = Registry()

EndpointCount = registry.gauge("endpoint_count", "Number of endpoints managed")
EndpointRegenerationCount = registry.counter(
    "endpoint_regenerations_total",
    "Count of all endpoint regenerations",
    ("outcome",),
)
EndpointRegenerationTime = registry.histogram(
    "endpoint_regeneration_seconds",
    "Endpoint regeneration time",
)
PolicyRevision = registry.gauge("policy_max_revision", "Highest policy revision")
PolicyCount = registry.gauge("policy_count", "Number of policy rules loaded")
PolicyImportErrors = registry.counter(
    "policy_import_errors_total", "Number of policy imports that failed"
)
DropCount = registry.counter(
    "drop_count_total", "Dropped packets/requests", ("reason", "direction")
)
ForwardCount = registry.counter(
    "forward_count_total", "Forwarded packets/requests", ("direction",)
)
ProxyVerdicts = registry.counter(
    "proxy_verdicts_total", "L7 proxy verdicts", ("l7_protocol", "verdict")
)
ProxyBatches = registry.counter(
    "proxy_batches_total", "Device verdict batches dispatched"
)
KvstoreDegraded = registry.gauge(
    "kvstore_degraded",
    "1 while the cluster store is fenced/unreachable and the agent "
    "serves from cached identities (reference: kvstore connectivity "
    "in `cilium status`)",
)
KvstoreDegradedEvents = registry.counter(
    "kvstore_degraded_events_total",
    "Transitions into kvstore degraded mode",
)

# Sidecar verdict-path overload & fault containment.  The degradation
# ladder is device -> quarantine -> host fallback -> shed; every rung
# is observable here and in `cilium sidecar status`.
SidecarShedTotal = registry.counter(
    "sidecar_shed_total",
    "Verdict entries shed with a typed SHED response "
    "(queue_full | deadline | stall)",
    ("reason",),
)
SidecarBatchCrashes = registry.counter(
    "sidecar_batch_crashes_total",
    "Dispatch rounds that crashed; every in-flight entry received a "
    "typed error verdict",
)
SidecarFallbackVerdicts = registry.counter(
    "sidecar_fallback_verdicts_total",
    "Verdict entries served by the bit-identical host/oracle fallback "
    "while the device was quarantined",
)
DeviceStalls = registry.counter(
    "device_stalls_total",
    "Device calls that exceeded the watchdog deadline",
)
DeviceQuarantined = registry.gauge(
    "device_quarantined",
    "1 while the verdict device/engine is quarantined and verdicts flow "
    "through the host fallback",
)
DeviceQuarantineEvents = registry.counter(
    "device_quarantine_events_total",
    "Transitions into device quarantine",
)
SidecarQueueDepth = registry.gauge(
    "sidecar_queue_depth",
    "Verdict admission-queue depth (entries) sampled per dispatch round",
)
SidecarClientReconnects = registry.counter(
    "sidecar_client_reconnects_total",
    "Successful shim-client reconnects to the verdict service",
)
FlowBufferOverflows = registry.counter(
    "flow_buffer_overflow_total",
    "Flows dropped for exceeding the retained-bytes cap without a "
    "frame boundary (typed protocol-error DROP + close)",
    ("proto",),
)
