"""Metrics registry with Prometheus text exposition.

reference: pkg/metrics/metrics.go:51-430 — counters/gauges/histograms for
endpoint counts, regeneration times, policy revision, drop/forward counts,
proxy redirects; exported in Prometheus text format.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Iterable

NAMESPACE = "cilium_tpu"


def _fmt_labels(label_names, label_values) -> str:
    if not label_names:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in zip(label_names, label_values)
    )
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple = ()) -> None:
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}
        self._mutex = threading.Lock()

    def inc(self, *label_values, amount: float = 1.0) -> None:
        with self._mutex:
            self._values[label_values] = self._values.get(label_values, 0.0) + amount

    def get(self, *label_values) -> float:
        return self._values.get(label_values, 0.0)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        if not self._values and not self.label_names:
            yield f"{self.name} 0"
        for lv, v in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(self.label_names, lv)} {v:g}"


class Gauge:
    def __init__(self, name: str, help_: str, label_names: tuple = ()) -> None:
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}
        self._mutex = threading.Lock()

    def set(self, value: float, *label_values) -> None:
        with self._mutex:
            self._values[label_values] = value

    def inc(self, *label_values, amount: float = 1.0) -> None:
        with self._mutex:
            self._values[label_values] = self._values.get(label_values, 0.0) + amount

    def dec(self, *label_values) -> None:
        self.inc(*label_values, amount=-1.0)

    def get(self, *label_values) -> float:
        return self._values.get(label_values, 0.0)

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        if not self._values and not self.label_names:
            yield f"{self.name} 0"
        for lv, v in sorted(self._values.items()):
            yield f"{self.name}{_fmt_labels(self.label_names, lv)} {v:g}"


DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10
)

# Microsecond-scale buckets (seconds) for the verdict-path stage
# histograms: DEFAULT_BUCKETS starts at 5ms, which is useless against a
# <1ms p99 target — every observation would land in the first bucket.
# 1µs resolution at the bottom, 100ms at the top (anything slower is a
# stall, not a latency distribution).
MICRO_BUCKETS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5,
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1,
)

# Sub-millisecond-to-seconds buckets for end-to-end verdict latency:
# the budgeted region (<1ms) keeps 50µs resolution; the tail out to
# 10s exists to see shed/stall behavior, not to be lived in.
SUBMS_BUCKETS = (
    5e-5, 1e-4, 2.5e-4, 5e-4, 7.5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


class Histogram:
    """Prometheus-style histogram.  ``observe`` is O(1) — one bisect
    plus one bucket increment under the mutex (it sits on the verdict
    hot path, once per stage per ROUND); the cumulative-bucket
    semantics the text format requires are computed at collect time."""

    def __init__(
        self, name: str, help_: str, label_names: tuple = (),
        buckets: tuple = DEFAULT_BUCKETS,
    ) -> None:
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets))
        # Per-bucket (NON-cumulative) counts; overflow (> last bound)
        # lives only in _totals (the +Inf bucket).
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}
        self._mutex = threading.Lock()

    def observe(self, value: float, *label_values) -> None:
        j = bisect_left(self.buckets, value)
        with self._mutex:
            counts = self._counts.get(label_values)
            if counts is None:
                counts = self._counts[label_values] = [0] * len(self.buckets)
            if j < len(counts):
                counts[j] += 1
            self._sums[label_values] = self._sums.get(label_values, 0.0) + value
            self._totals[label_values] = self._totals.get(label_values, 0) + 1

    def get_count(self, *label_values) -> int:
        return self._totals.get(label_values, 0)

    def get_sum(self, *label_values) -> float:
        return self._sums.get(label_values, 0.0)

    def quantile(self, q: float, *label_values) -> float | None:
        """Upper bucket bound at quantile ``q`` (conservative — the true
        value is <= the returned bound unless it overflowed the last
        bucket, in which case the last bound is returned).  None when
        nothing was observed."""
        with self._mutex:
            total = self._totals.get(label_values, 0)
            if not total:
                return None
            counts = list(self._counts.get(label_values, ()))
        target = q * total
        running = 0
        for j, b in enumerate(self.buckets):
            running += counts[j] if j < len(counts) else 0
            if running >= target:
                return b
        return self.buckets[-1] if self.buckets else None

    def collect(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._mutex:
            snap = {
                lv: (list(self._counts.get(lv, ())), self._sums.get(lv, 0.0),
                     self._totals[lv])
                for lv in self._totals
            }
        for lv in sorted(snap):
            counts, sum_, total = snap[lv]
            running = 0
            for j, b in enumerate(self.buckets):
                # Cumulative buckets: le is inclusive, every bucket
                # counts all observations <= its bound.
                running += counts[j] if j < len(counts) else 0
                labels = _fmt_labels(
                    self.label_names + ("le",), lv + (f"{b:g}",)
                )
                yield f"{self.name}_bucket{labels} {running}"
            labels_inf = _fmt_labels(self.label_names + ("le",), lv + ("+Inf",))
            yield f"{self.name}_bucket{labels_inf} {total}"
            yield (
                f"{self.name}_sum{_fmt_labels(self.label_names, lv)} "
                f"{sum_:g}"
            )
            yield (
                f"{self.name}_count{_fmt_labels(self.label_names, lv)} "
                f"{total}"
            )


class Registry:
    def __init__(self) -> None:
        self._collectors: list = []
        self._mutex = threading.Lock()

    def register(self, collector):
        with self._mutex:
            self._collectors.append(collector)
        return collector

    def counter(self, name, help_, label_names=()):
        return self.register(Counter(f"{NAMESPACE}_{name}", help_, label_names))

    def gauge(self, name, help_, label_names=()):
        return self.register(Gauge(f"{NAMESPACE}_{name}", help_, label_names))

    def histogram(self, name, help_, label_names=(), buckets=DEFAULT_BUCKETS):
        return self.register(
            Histogram(f"{NAMESPACE}_{name}", help_, label_names, buckets)
        )

    def expose(self) -> str:
        """Prometheus text format."""
        lines: list[str] = []
        with self._mutex:
            collectors = list(self._collectors)
        for c in collectors:
            lines.extend(c.collect())
        return "\n".join(lines) + "\n"


# Global registry + the reference's core metric set
# (reference: pkg/metrics/metrics.go:51-430).
registry = Registry()

EndpointCount = registry.gauge("endpoint_count", "Number of endpoints managed")
EndpointRegenerationCount = registry.counter(
    "endpoint_regenerations_total",
    "Count of all endpoint regenerations",
    ("outcome",),
)
EndpointRegenerationTime = registry.histogram(
    "endpoint_regeneration_seconds",
    "Endpoint regeneration time",
)
PolicyRevision = registry.gauge("policy_max_revision", "Highest policy revision")
PolicyCount = registry.gauge("policy_count", "Number of policy rules loaded")
PolicyImportErrors = registry.counter(
    "policy_import_errors_total", "Number of policy imports that failed"
)
DropCount = registry.counter(
    "drop_count_total", "Dropped packets/requests", ("reason", "direction")
)
ForwardCount = registry.counter(
    "forward_count_total", "Forwarded packets/requests", ("direction",)
)
ProxyVerdicts = registry.counter(
    "proxy_verdicts_total", "L7 proxy verdicts", ("l7_protocol", "verdict")
)
ProxyBatches = registry.counter(
    "proxy_batches_total", "Device verdict batches dispatched"
)
KvstoreDegraded = registry.gauge(
    "kvstore_degraded",
    "1 while the cluster store is fenced/unreachable and the agent "
    "serves from cached identities (reference: kvstore connectivity "
    "in `cilium status`)",
)
KvstoreDegradedEvents = registry.counter(
    "kvstore_degraded_events_total",
    "Transitions into kvstore degraded mode",
)

# Sidecar verdict-path overload & fault containment.  The degradation
# ladder is device -> quarantine -> host fallback -> shed; every rung
# is observable here and in `cilium sidecar status`.
SidecarShedTotal = registry.counter(
    "sidecar_shed_total",
    "Verdict entries shed with a typed SHED response "
    "(queue_full | deadline | stall | session_quota | "
    "session_quarantined)",
    ("reason",),
)
SidecarBatchCrashes = registry.counter(
    "sidecar_batch_crashes_total",
    "Dispatch rounds that crashed; every in-flight entry received a "
    "typed error verdict",
)
SidecarFallbackVerdicts = registry.counter(
    "sidecar_fallback_verdicts_total",
    "Verdict entries served by the bit-identical host/oracle fallback "
    "while the device was quarantined",
)
DeviceStalls = registry.counter(
    "device_stalls_total",
    "Device calls that exceeded the watchdog deadline",
)
DeviceQuarantined = registry.gauge(
    "device_quarantined",
    "1 while the verdict device/engine is quarantined and verdicts flow "
    "through the host fallback",
)
DeviceQuarantineEvents = registry.counter(
    "device_quarantine_events_total",
    "Transitions into device quarantine",
)
SidecarQueueDepth = registry.gauge(
    "sidecar_queue_depth",
    "Verdict admission-queue depth (entries) sampled per dispatch round",
)
SidecarClientReconnects = registry.counter(
    "sidecar_client_reconnects_total",
    "Successful shim-client reconnects to the verdict service",
)
SidecarTransportFallback = registry.counter(
    "sidecar_transport_fallback_total",
    "Shared-memory transport work served on the socket rung instead "
    "(per-batch: ring_full | oversize | verdict_ring_full; session "
    "demotions: torn_slot | generation_mismatch | attach_rejected | "
    "disabled | peer_death | oversize_spree)",
    ("reason",),
)
# Multi-tenant fan-in (N shims, one sidecar): every containment action
# is SESSION-scoped and typed — the operator can attribute a shed or a
# quarantine to one pod.  The session label is the shim's announced
# identity, stable across its reconnects, drawn from a BOUNDED
# vocabulary (the service caps distinct label values; identities past
# the cap report as 'other', unnamed sessions as 'unnamed' — the full
# identity is always in status rows), so a shim cycling names cannot
# grow cardinality without bound.
SidecarSessionShed = registry.counter(
    "sidecar_session_shed_total",
    "Verdict entries shed with a typed response, attributed to the "
    "session that submitted them (session_quota | session_quarantined "
    "| queue_full | deadline | stall | error)",
    ("session", "reason"),
)
SidecarSessionQuarantines = registry.counter(
    "sidecar_session_quarantines_total",
    "Session-scoped quarantine latches (flood | reconnect_storm): the "
    "named session's data plane is answered typed-SHED for a cooldown "
    "while every other session keeps serving",
    ("session", "reason"),
)
SidecarSessionDeaths = registry.counter(
    "sidecar_session_deaths_total",
    "Shim sessions torn down, by how they died (closed | abrupt | "
    "send_timeout | write_failed)",
    ("reason",),
)
SidecarSessionsActive = registry.gauge(
    "sidecar_sessions_active",
    "Live shim sessions currently attached to the verdict service",
)
SidecarShmReclaims = registry.counter(
    "sidecar_shm_segments_reclaimed_total",
    "Orphaned shared-memory segments unlinked by the service after "
    "lease expiry (a shim died without MSG_SHM_DETACH; the creator "
    "would otherwise leak the /dev/shm files until reboot)",
)
SidecarStaleSegmentsSwept = registry.counter(
    "sidecar_shm_stale_segments_swept_total",
    "Dead-owner /dev/shm segments force-unlinked by the STARTUP sweep "
    "(a crashed predecessor's orphans, past lease — the in-service "
    "lease timers died with it, so the successor reclaims at boot)",
)
# Hitless restart (sidecar/service.py handoff): generation is the
# fencing token — a surrendered predecessor is a zombie whose late
# writes are rejected typed, never silently dropped.
SidecarRestartGeneration = registry.gauge(
    "sidecar_restart_generation",
    "This service's restart generation (monotonic across graceful "
    "handoffs; 1 = cold boot with no adopted snapshot)",
)
SidecarHandoffSurrenders = registry.counter(
    "sidecar_handoff_surrenders_total",
    "Handoff snapshots surrendered to a successor (this process "
    "fenced itself, quiesced in-flight rounds and released the "
    "socket path)",
)
SidecarFenceRejects = registry.counter(
    "sidecar_fence_rejects_total",
    "Late writes rejected typed by a fenced zombie predecessor "
    "(policy_update | data | new_connection)",
    ("kind",),
)
SidecarSurvivalHits = registry.counter(
    "sidecar_client_survival_hits_total",
    "Frames answered from the shim-local grant table while the "
    "sidecar was away (restart survival window open: grants served "
    "until replay revalidates or the grace deadline revokes them)",
)
# Policy-table epoch churn (sidecar/service.py): each successful
# compile-then-swap bumps the epoch gauge; failures are typed and the
# OLD epoch keeps serving (fail-closed — a failed recompile is never a
# policy outage).
PolicySwapsTotal = registry.counter(
    "policy_swaps_total",
    "Successful policy-table epoch swaps (staged build committed by "
    "one pointer flip under the round-snapshot lock)",
)
PolicySwapFailures = registry.counter(
    "policy_swap_failures_total",
    "Policy updates rejected fail-closed with the old epoch still "
    "serving (parse | host-compile | device-build | parity | "
    "ack-timeout | shutdown)",
    ("reason",),
)
PolicySwapSeconds = registry.histogram(
    "policy_swap_seconds",
    "Duration of the swap pointer flip (lock hold; the off-path "
    "staged build is NOT included)",
    buckets=MICRO_BUCKETS,
)
PolicyEpochGauge = registry.gauge(
    "policy_table_epoch",
    "Committed policy-table epoch (monotonic; bumped per swap)",
)
# Multi-chip sharded serving (parallel/rulesharding.py + sidecar
# service mesh rung): a lost/erroring mesh device demotes the whole
# service to the single-chip fallback executables — typed, counted,
# and bit-identical by the sharding parity contract.
MeshDemotions = registry.counter(
    "mesh_demotions_total",
    "Sharded (multi-chip) serving demoted to the single-chip fallback "
    "executables (device-call | device-stall), typed by reason; the "
    "service keeps serving, never a wedged round",
    ("reason",),
)
MeshActive = registry.gauge(
    "mesh_active",
    "1 while the (flows, rules) device mesh serves verdicts, 0 when "
    "off or demoted",
)
MeshRebindRebuilds = registry.counter(
    "mesh_rebind_rebuilds_total",
    "Demotion-era engines (built single-chip while the mesh rung was "
    "demoted) re-sharded by the heal's queued off-path rebuilds "
    "(ROADMAP 1c: the re-promotion flip queues a rebind per stranded "
    "engine instead of waiting for the next epoch swap)",
)
MeshRepromotions = registry.counter(
    "mesh_repromotions_total",
    "Demoted sharded serving re-promoted after a timed off-path "
    "re-probe (one sharded executable rebuilt, parity-probed against "
    "the single-chip fallback, then one pointer flip back)",
)
MeshReshapes = registry.counter(
    "mesh_reshapes_total",
    "Width-ladder reshapes: sharded serving rebuilt over the "
    "surviving device subset at a reduced bucketable width after a "
    "partial device loss (the fallback rung covers only the rebuild "
    "window, not until restart)",
)
MeshCapacity = registry.gauge(
    "mesh_capacity_fraction",
    "Serving capacity of the current mesh rung as a fraction of the "
    "full mesh (1.0 full, width ratio reshaped, 1/width fallback); "
    "admission (shed queue depth, DRR credit windows) scales by it so "
    "a degraded mesh sheds typed at its actual capacity",
)
MeshLostDevices = registry.gauge(
    "mesh_lost_devices",
    "Devices currently attributed lost by the per-device health table "
    "(readback error, stall, or vanishing from the backend device set)",
)
# Established-flow verdict cache (sidecar service Phase-A mask +
# _classify_entry, shim client pre-push short-circuit, engine judge
# steps).  Every hit is a device round, a wire round-trip, and a
# reassembly pass that never happens; every cached verdict is
# attributed to the ORIGINAL rule row under the epoch it was derived
# at (flowlog path label "cached").
VerdictCacheHits = registry.counter(
    "verdict_cache_hits_total",
    "Frames short-circuited by the established-flow verdict cache, by "
    "site (shim = bytes never pushed across the transport, service = "
    "sidecar Phase-A/entry mask, engine = judge-step host answer)",
    ("site",),
)
VerdictCacheMisses = registry.counter(
    "verdict_cache_misses_total",
    "Request-direction entries that reached the device path with the "
    "verdict cache enabled (no byte-invariance claim, stale epoch, or "
    "residue kept the flow off the cache tier)",
)
VerdictCacheInvalidations = registry.counter(
    "verdict_cache_invalidations_total",
    "Cache rows killed wholesale: epoch pointer-flips (the epoch key "
    "makes stale hits structurally impossible; this counts the armed "
    "rows each flip retired) and quarantine/close disarms",
    ("reason",),
)
VerdictCacheEvictions = registry.counter(
    "verdict_cache_evictions_total",
    "Armed rows evicted LRU-by-last-hit at the flow_cache_entries "
    "cap (capacity management, not invalidation: the victim's claim "
    "stays true for its epoch, so delivered shim grants need no "
    "revoke)",
)
FlowBufferOverflows = registry.counter(
    "flow_buffer_overflow_total",
    "Flows dropped for exceeding the retained-bytes cap without a "
    "frame boundary (typed protocol-error DROP + close)",
    ("proto",),
)

# Verdict-path latency decomposition (sidecar/trace.py).  Stage
# histograms are observed once per STAGE per dispatch ROUND (amortized
# — never per entry), labeled by serving path:
#   vec    — vectorized device path (matrix/vec rounds)
#   oracle — entrywise slow path (engines + in-process parsers)
#   host   — quarantine host-fallback rounds
#   shed   — typed SHED (queue_full / deadline / stall)
VerdictStageSeconds = registry.histogram(
    "verdict_stage_seconds",
    "Per-round verdict latency by stage: queue (admit->pop), "
    "batch_form, device_submit (host-side dispatch), device (fenced "
    "readback), drain, send",
    ("stage", "path"),
    buckets=MICRO_BUCKETS,
)
VerdictE2ESeconds = registry.histogram(
    "verdict_e2e_seconds",
    "End-to-end verdict latency (wire ingress -> verdict frame "
    "written), one observation per wire batch",
    ("path",),
    buckets=SUBMS_BUCKETS,
)
VerdictBatchOccupancy = registry.gauge(
    "verdict_batch_occupancy",
    "Entries in the last dispatch round / configured batch capacity",
)
DeviceBusyFraction = registry.gauge(
    "verdict_device_busy_fraction",
    "Fraction of wall-clock spent in the device stage (fenced "
    "submit->complete), windowed over the last ~1s of rounds",
)
VerdictTraceSpans = registry.counter(
    "verdict_trace_spans_total",
    "Per-entry verdict spans captured by the trace ring "
    "(sample = 1-in-N, slow = exceeded the slow threshold, "
    "shed = typed SHED exemplar)",
    ("kind",),
)

# Flow-level verdict observability (flowlog/): one increment per
# distinct (verdict, path, match_kind) tuple per ROUND — the counter
# twin of the flow-record ring, so dashboards see verdict mix by
# serving path and by how the deciding rule was compiled.
FlowVerdictsTotal = registry.counter(
    "flow_verdicts_total",
    "Flow verdict records by verdict, serving path, and the deciding "
    "rule's compiled match kind (literal|regex|nfa|l3|l4)",
    ("verdict", "path", "match_kind"),
)

# Kvstore traffic/fencing counters bridged from KvstoreCounters
# (kvstore/net.py): every named event increments here too, so the
# store's failure/fencing behavior shows up in /metrics instead of
# only in status RPCs.
KvstoreEvents = registry.counter(
    "kvstore_events_total",
    "Kvstore server/client event counters (fencing, replication, "
    "transport failures) bridged from kvstore/net.py KvstoreCounters",
    ("scope", "event"),
)

# Flight-recorder surface (sidecar/blackbox.py).  ServingTier unifies
# the per-subsystem degradation ladders into ONE scrapeable gauge —
# 0 is the full-speed rung, higher is narrower (mesh: full/reshaped/
# fallback = 0/1/2; guard: serving/quarantined = 0/1; cache: armed/
# disarmed = 0/1; transport: shm/socket = 0/1) — fed from the same
# typestate-observer hook that feeds the incident timeline.  Set only
# on tier CHANGE (control-plane transitions), never per entry.
ServingTier = registry.gauge(
    "serving_tier",
    "Current degradation-ladder rung per subsystem (0 = full speed, "
    "higher = narrower serving tier), unified across mesh, device "
    "guard, flow cache, and shm transport",
    ("subsystem",),
)
SidecarPostmortems = registry.counter(
    "sidecar_postmortem_bundles_total",
    "Postmortem bundles written by the flight recorder on fail-closed "
    "transitions, labeled by the triggering typestate table (or "
    "'mark' for non-typestate markers)",
    ("trigger",),
)

# Device-economics ledger (sidecar/ledger.py).  Two halves: the
# compile ledger answers "why did a compile happen" (cause taxonomy:
# cold / prewarm / churn-new-shape / churn-vocab / mesh-reshape /
# repromotion / heal-rebind) and the formation half answers "why was
# a batch issued" (trigger taxonomy: size-full / flush / deadline /
# idle-greedy / cut-through).  Compile metrics fire per COMPILE
# (control-plane rate); formation metrics fire once per ROUND, never
# per entry.
DeviceCompilesTotal = registry.counter(
    "device_compiles_total",
    "Executable-producing traces/compiles recorded by the device "
    "ledger, by cause (cold|prewarm|churn-new-shape|churn-vocab|"
    "mesh-reshape|repromotion|heal-rebind) and engine family",
    ("cause", "family"),
)
DeviceCompileSeconds = registry.histogram(
    "device_compile_seconds",
    "Wall seconds per recorded trace/compile, by cause",
    ("cause",),
    buckets=DEFAULT_BUCKETS,
)
ExecutablesResident = registry.gauge(
    "device_executables_resident",
    "Shape-keyed executables currently resident in the serving "
    "caches — the single definition shared by prewarm bookkeeping "
    "and the SHAPE_CACHE_MAX eviction path",
)
BatchFormationRounds = registry.counter(
    "batch_formation_rounds_total",
    "Dispatch rounds by formation trigger (size-full|flush|deadline|"
    "idle-greedy|cut-through) — one increment per round",
    ("trigger",),
)
BatchFormationAge = registry.histogram(
    "batch_formation_oldest_age_seconds",
    "Oldest-entry queue age at pop per dispatch round, by formation "
    "trigger — one observation per round",
    ("trigger",),
    buckets=MICRO_BUCKETS,
)
DrrOutstandingBytes = registry.gauge(
    "drr_outstanding_bytes",
    "Byte-weighted outstanding work across per-session DRR windows "
    "(payload bytes admitted to the dispatcher and not yet popped)",
)
