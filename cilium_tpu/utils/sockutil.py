"""Socket teardown helper — THE one definition of shutdown-then-close.

A bare ``close()`` on a socket another thread is blocked on
(``accept()``/``recv()``) does not tear the kernel object down: the
close is deferred until that call returns, which only the teardown
would have made happen.  PR 2 fixed this by hand in the verdict
service (zombie listener kept accepting into a dead service) and the
sidecar client (reader parked in recv to process exit); cilium-lint
rule R3 now flags the pattern tree-wide and this helper is the fix it
points at: ``shutdown(SHUT_RDWR)`` first — which wakes any blocked
peer and accept/recv callers — then ``close()``.
"""

from __future__ import annotations

import socket


def shutdown_close(sock: socket.socket | None) -> None:
    """Shutdown (waking any thread blocked on the socket) then close.
    Both steps swallow OSError: teardown must be callable from any
    state — never-connected, already shut down, already closed."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass
