"""Centralized defaults (reference: pkg/defaults/defaults.go)."""

from __future__ import annotations

# Runtime paths
RUNTIME_PATH = "/var/run/cilium-tpu"
STATE_DIR = "state"
SOCK_PATH = RUNTIME_PATH + "/cilium-tpu.sock"
MONITOR_SOCK_PATH = RUNTIME_PATH + "/monitor.sock"
ACCESS_LOG_SOCK_PATH = RUNTIME_PATH + "/access_log.sock"

# Proxy port allocation range (reference: daemon/daemon.go:1327).
PROXY_PORT_MIN = 10000
PROXY_PORT_MAX = 20000

# Identity (reference: pkg/identity minimal user identity).
MIN_USER_IDENTITY = 256
MAX_IDENTITY = (1 << 24) - 1

# Cluster
CLUSTER_NAME = "default"

# Endpoint builders (reference: daemon/daemon.go:1623 — min 4 or NumCPU).
MIN_ENDPOINT_BUILDERS = 4

# Device batch defaults (TPU runtime, not in the reference).
BATCH_FLOWS = 2048
BATCH_WIDTH = 256
BATCH_TIMEOUT_MS = 0.5  # adaptive batching deadline toward <1ms p99

# Monitor
MONITOR_QUEUE_SIZE = 65536

# kvstore
KVSTORE_LEASE_TTL = 15.0  # seconds
KVSTORE_STALE_LOCK_TIMEOUT = 30.0
