"""Lock wrappers with opt-in debug instrumentation.

reference: pkg/lock — ``lock_fast.go`` aliases sync.Mutex/RWMutex in
production builds; the ``lockdebug`` build tag swaps in deadlock-aware
wrappers that (a) warn when a lock is HELD longer than a selfish
threshold (lock_debug.go selfishThresholdSec 0.1s) and (b) treat
waiting longer than a deadlock timeout as a deadlock and dump stacks.

The Python analog keeps the same two-mode shape: with debug disabled
(default) Mutex/RWMutex add one attribute read over the bare primitive;
``enable_debug()`` turns on hold-time warnings, acquisition-timeout
stack dumps, and same-thread double-acquire detection (Python locks
don't deadlock on re-entry the way a waiting goroutine does — a
non-reentrant re-acquire IS the deadlock, so it raises).
"""

from __future__ import annotations

import logging
import threading
import time
import traceback

log = logging.getLogger(__name__)

SELFISH_THRESHOLD = 0.1  # reference: lock_debug.go selfishThresholdSec
DEADLOCK_TIMEOUT = 310.0  # reference: lock_debug.go deadLockTimeout

_debug = False


def enable_debug() -> None:
    global _debug
    _debug = True


def disable_debug() -> None:
    global _debug
    _debug = False


def debug_enabled() -> bool:
    return _debug


class Mutex:
    """sync.Mutex analog; context-manager usable."""

    def __init__(self, name: str = "") -> None:
        self._lock = threading.Lock()
        self.name = name
        self._owner: int | None = None
        self._acquired_at = 0.0

    def acquire(self, timeout: float | None = None) -> bool:
        """Blocking acquire (timeout=None) never returns False: in
        debug mode a wait past DEADLOCK_TIMEOUT logs stacks and KEEPS
        WAITING (report-don't-steal), so mutual exclusion is identical
        to non-debug mode.  A caller-supplied timeout is plain try-lock
        semantics — its expiry is never treated as a deadlock."""
        me = threading.get_ident()
        if timeout is not None:
            ok = self._lock.acquire(timeout=timeout)
            if ok:
                self._owner = me
                self._acquired_at = time.monotonic()
            return ok
        if _debug:
            if self._owner == me:
                # A non-reentrant self re-acquire can never succeed:
                # report the deadlock immediately instead of hanging.
                raise RuntimeError(
                    f"deadlock: thread re-acquiring mutex {self.name!r} "
                    "it already holds"
                )
            waited = 0.0
            while not self._lock.acquire(timeout=DEADLOCK_TIMEOUT):
                waited += DEADLOCK_TIMEOUT
                log.error(
                    "possible deadlock: waited %.0fs for %r; stacks:\n%s",
                    waited, self.name, _all_stacks(),
                )
        else:
            self._lock.acquire()
        self._owner = me
        self._acquired_at = time.monotonic()
        return True

    def release(self) -> None:
        if _debug and self._owner is not None:
            held = time.monotonic() - self._acquired_at
            if held > SELFISH_THRESHOLD:
                log.warning(
                    "lock %r held for %.3fs (> %.2fs)",
                    self.name, held, SELFISH_THRESHOLD,
                )
        # Owner is tracked in every mode so toggling debug on at
        # runtime never sees a stale owner.
        self._owner = None
        self._lock.release()

    def __enter__(self) -> "Mutex":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class RWMutex:
    """sync.RWMutex analog: many readers or one writer.  Writer
    preference: arriving writers block new readers so writers cannot
    starve (matching Go's RWMutex contract)."""

    def __init__(self, name: str = "") -> None:
        self.name = name
        self._cond = threading.Condition()
        self._readers = 0
        self._writer: int | None = None
        self._writers_waiting = 0
        self._acquired_at = 0.0

    def r_acquire(self) -> None:
        with self._cond:
            if _debug and self._writer == threading.get_ident():
                raise RuntimeError(
                    f"deadlock: RLock of {self.name!r} while holding "
                    "its write lock"
                )
            deadline = time.monotonic() + DEADLOCK_TIMEOUT
            while self._writer is not None or self._writers_waiting:
                if not self._cond.wait(timeout=deadline - time.monotonic()):
                    if _debug:
                        log.error(
                            "possible deadlock: reader waited %.0fs for "
                            "%r; stacks:\n%s",
                            DEADLOCK_TIMEOUT, self.name, _all_stacks(),
                        )
                    deadline = time.monotonic() + DEADLOCK_TIMEOUT
            self._readers += 1

    def r_release(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire(self) -> None:
        with self._cond:
            me = threading.get_ident()
            if _debug and self._writer == me:
                raise RuntimeError(
                    f"deadlock: thread re-acquiring write lock "
                    f"{self.name!r} it already holds"
                )
            self._writers_waiting += 1
            try:
                deadline = time.monotonic() + DEADLOCK_TIMEOUT
                while self._writer is not None or self._readers:
                    if not self._cond.wait(
                        timeout=deadline - time.monotonic()
                    ):
                        if _debug:
                            log.error(
                                "possible deadlock: writer waited %.0fs "
                                "for %r; stacks:\n%s",
                                DEADLOCK_TIMEOUT, self.name, _all_stacks(),
                            )
                        deadline = time.monotonic() + DEADLOCK_TIMEOUT
            finally:
                self._writers_waiting -= 1
            self._writer = me
            self._acquired_at = time.monotonic()

    def release(self) -> None:
        with self._cond:
            if _debug:
                held = time.monotonic() - self._acquired_at
                if held > SELFISH_THRESHOLD:
                    log.warning(
                        "write lock %r held for %.3fs (> %.2fs)",
                        self.name, held, SELFISH_THRESHOLD,
                    )
            self._writer = None
            self._cond.notify_all()

    def __enter__(self) -> "RWMutex":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    class _ReadGuard:
        def __init__(self, rw: "RWMutex") -> None:
            self.rw = rw

        def __enter__(self):
            self.rw.r_acquire()
            return self

        def __exit__(self, *exc):
            self.rw.r_release()

    def read(self) -> "_ReadGuard":
        """``with rw.read():`` — reader-side context manager."""
        return RWMutex._ReadGuard(self)


def _all_stacks() -> str:
    import sys

    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- {names.get(ident, '?')} ({ident}) ---")
        out.extend(s.rstrip() for s in traceback.format_stack(frame))
    return "\n".join(out)
