"""Named reconciliation controllers with interval + error backoff.

reference: pkg/controller/controller.go — every long-running reconciliation
loop is a named Controller: runs DoFunc on RunInterval, retries with
linearly-growing backoff on error, tracks success/failure counters, and is
surfaced by ``status --all-controllers``.
"""

from __future__ import annotations

import threading
import time
import traceback
import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class ControllerParams:
    """reference: controller.go:50."""

    do_func: Optional[Callable[[], None]] = None
    stop_func: Optional[Callable[[], None]] = None
    run_interval: float = 0.0  # seconds; 0 = run once + on update only
    error_retry_base: float = 1.0  # multiplied by consecutive error count
    no_error_retry: bool = False


@dataclass
class ControllerStatus:
    name: str
    uuid: str
    success_count: int
    failure_count: int
    consecutive_errors: int
    last_error: str
    last_duration: float


class Controller:
    """reference: controller.go:128."""

    def __init__(self, name: str, params: ControllerParams) -> None:
        self.name = name
        self.uuid = str(uuid_mod.uuid4())
        self.params = params
        self.mutex = threading.RLock()
        self.success_count = 0
        self.failure_count = 0
        self.consecutive_errors = 0
        self.last_error: str = ""
        self.last_duration = 0.0
        self.last_success_stamp = 0.0
        self.last_error_stamp = 0.0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._terminated = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"ctrl-{name}", daemon=True
        )
        self._thread.start()

    def _run_once(self) -> None:
        start = time.monotonic()
        try:
            if self.params.do_func is None:
                raise RuntimeError("controller has unset DoFunc")
            self.params.do_func()
        except Exception as e:  # noqa: BLE001 — controllers never die on errors
            with self.mutex:
                self.failure_count += 1
                self.consecutive_errors += 1
                self.last_error = f"{e}"
                self.last_error_stamp = time.time()
                self.last_duration = time.monotonic() - start
        else:
            with self.mutex:
                self.success_count += 1
                self.consecutive_errors = 0
                self.last_error = ""
                self.last_success_stamp = time.time()
                self.last_duration = time.monotonic() - start

    def _next_interval(self) -> float:
        """Error backoff: base * consecutive errors (reference:
        controller.go:70-74), else the regular run interval."""
        with self.mutex:
            errs = self.consecutive_errors
        if errs > 0 and not self.params.no_error_retry:
            return self.params.error_retry_base * errs
        if self.params.run_interval > 0:
            return self.params.run_interval
        return 0.0

    def _run(self) -> None:
        self._run_once()
        while not self._stop.is_set():
            interval = self._next_interval()
            if interval <= 0:
                # No interval: wait for an explicit update/stop.
                self._wake.wait()
            else:
                self._wake.wait(timeout=interval)
            if self._stop.is_set():
                break
            self._wake.clear()
            self._run_once()
        if self.params.stop_func is not None:
            try:
                self.params.stop_func()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
        self._terminated.set()

    def update(self, params: ControllerParams | None = None) -> None:
        """Replace params and run immediately (reference:
        Manager.UpdateController semantics)."""
        if params is not None:
            self.params = params
        self._wake.set()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        self._terminated.wait(timeout)

    def status(self) -> ControllerStatus:
        with self.mutex:
            return ControllerStatus(
                name=self.name,
                uuid=self.uuid,
                success_count=self.success_count,
                failure_count=self.failure_count,
                consecutive_errors=self.consecutive_errors,
                last_error=self.last_error,
                last_duration=self.last_duration,
            )


class ControllerManager:
    """Collection of controllers keyed by name
    (reference: pkg/controller/manager.go)."""

    def __init__(self) -> None:
        self.controllers: dict[str, Controller] = {}
        self.mutex = threading.RLock()

    def update_controller(self, name: str, params: ControllerParams) -> Controller:
        with self.mutex:
            c = self.controllers.get(name)
            if c is not None:
                c.update(params)
                return c
            c = Controller(name, params)
            self.controllers[name] = c
            return c

    def remove_controller(self, name: str) -> bool:
        with self.mutex:
            c = self.controllers.pop(name, None)
        if c is None:
            return False
        c.stop()
        return True

    def remove_all(self) -> None:
        with self.mutex:
            cs = list(self.controllers.values())
            self.controllers.clear()
        for c in cs:
            c.stop()

    def lookup(self, name: str) -> Controller | None:
        return self.controllers.get(name)

    def statuses(self) -> list[ControllerStatus]:
        with self.mutex:
            return [c.status() for c in self.controllers.values()]


# Global manager used by subsystems (reference: controller package-level API).
manager = ControllerManager()
