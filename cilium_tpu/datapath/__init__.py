"""Datapath management: the array-native stand-in for BPF program loading.

reference: pkg/datapath — where the reference compiles and attaches BPF
programs (loader), manages the XDP prefilter (prefilter) and syncs routes,
this build packs host-side maps into device arrays (cilium_tpu.maps/ops)
and manages the prefilter deny-lists feeding the batched LPM op.
"""

from .prefilter import PreFilter

__all__ = ["PreFilter"]
