"""Composed IPv6 L3/L4 datapath step — the v6 twin of pipeline.py.

The batched analog of the reference's per-packet IPv6 egress pipeline
(reference: bpf/bpf_lxc.c:418 tail_handle_ipv6 → handle_ipv6_from_lxc):
the same five stages as the v4 pass — lb6 service translation, v6
conntrack, v6 ipcache LPM identity, policy cascade, verdict — with
every address carried as FOUR int32 word lanes (the word order of
ops/lpm.ipv6_to_words), so the whole dual-stack datapath shares one
policy table and one verdict vocabulary.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..maps.ctmap import CtKey6, CtMap
from ..maps.ipcache import IpcacheMap
from ..maps.lbmap import DeviceLb6Map, LbMap, lb6_select_backend_batch
from ..maps.policymap import (
    DIR_EGRESS,
    DevicePolicyMap,
    PolicyMap,
    policy_can_access_batch,
)
from ..ops.lpm import DeviceLpm, lpm_lookup
from ..ops.maplookup import DeviceTable, exact_lookup, pack_table, u32_to_i32
from .pipeline import DROP, FORWARD, TO_PROXY, WORLD_ID, flow_hash32


def flow_hash32_v6(saddr_w, daddr_w, sport, dport, proto):
    """v6 flow hash: fold the word lanes into the v4 hash shape so host
    and device agree (any fixed function works; see flow_hash32)."""
    s = saddr_w[0]
    d = daddr_w[0]
    for w in range(1, 4):
        s = s ^ (saddr_w[w] * np.int32(31))
        d = d ^ (daddr_w[w] * np.int32(131))
    return flow_hash32(s, d, sport, dport, proto)


@jax.tree_util.register_pytree_node_class
@dataclass
class DatapathTables6:
    """Device snapshot of the v6 maps."""

    ct: DeviceTable  # 11 cols: d0..d3, s0..s3, dport, sport, proto
    lb: DeviceLb6Map
    ipcache: DeviceLpm  # v6 (4-word)
    policy: DevicePolicyMap

    def tree_flatten(self):
        return ((self.ct, self.lb, self.ipcache, self.policy), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def pack_ct6(ct: CtMap) -> DeviceTable:
    """Snapshot live v6 CT entries (CtKey6) into an 11-column device
    exact-match table; expired entries are filtered like pack_ct."""
    now = int(ct.clock())
    live = [
        k for k, e in ct.entries.items()
        if e.lifetime >= now and isinstance(k, CtKey6)
    ]
    keys = np.zeros((len(live), 11), np.int64)
    for i, k in enumerate(live):
        keys[i, 0:4] = CtKey6.words(k.daddr)
        keys[i, 4:8] = CtKey6.words(k.saddr)
        keys[i, 8:11] = (k.dport, k.sport, k.nexthdr)
    vals = np.zeros((len(live), 1), np.int64)
    return pack_table(u32_to_i32(keys), vals)


def build_tables6(
    ct: CtMap, lb: LbMap, ipcache: IpcacheMap, policy: PolicyMap
) -> DatapathTables6:
    return DatapathTables6(
        ct=pack_ct6(ct),
        lb=lb.to_device6(),
        ipcache=ipcache.to_device(v6=True),
        policy=policy.to_device(),
    )


@jax.jit
def datapath_verdicts6(
    tables: DatapathTables6,
    saddr_w,  # 4-tuple of [F] int32 word arrays
    daddr_w,  # 4-tuple of [F] int32
    sport: jax.Array,
    dport: jax.Array,
    proto: jax.Array,
):
    """One composed v6 device pass; mirrors datapath_verdicts' output
    dict with new_daddr_words instead of new_daddr."""
    saddr_w = tuple(jnp.asarray(w, jnp.int32) for w in saddr_w)
    daddr_w = tuple(jnp.asarray(w, jnp.int32) for w in daddr_w)
    sport = jnp.asarray(sport, jnp.int32)
    dport = jnp.asarray(dport, jnp.int32)
    proto = jnp.asarray(proto, jnp.int32)

    # 1. lb6 service translation (reference: lb.h lb6_lookup_service).
    fh = flow_hash32_v6(saddr_w, daddr_w, sport, dport, proto)
    svc_found, be_words, be_port, rev_nat = lb6_select_backend_batch(
        tables.lb, daddr_w, dport, fh
    )
    new_daddr_w = tuple(
        jnp.where(svc_found, be_words[w], daddr_w[w]) for w in range(4)
    )
    new_dport = jnp.where(svc_found, be_port, dport)

    # 2. v6 conntrack on the post-DNAT tuple.
    est, _ = exact_lookup(
        tables.ct, *new_daddr_w, *saddr_w, new_dport, sport, proto
    )

    # 3. Destination identity from the v6 ipcache LPM.
    ip_found, ident, _plen = lpm_lookup(tables.ipcache, *new_daddr_w)
    dst_id = jnp.where(ip_found, ident, jnp.int32(WORLD_ID))

    # 4. Policy cascade (identity-based — shared with v4).
    allowed, proxy_port = policy_can_access_batch(
        tables.policy, dst_id, new_dport, proto, direction=DIR_EGRESS
    )

    pass_ok = est | allowed
    verdict = jnp.where(
        pass_ok,
        jnp.where((proxy_port > 0) & ~est, TO_PROXY, FORWARD),
        DROP,
    )
    return {
        "verdict": verdict,
        "new_daddr_words": new_daddr_w,
        "new_dport": new_dport,
        "dst_identity": dst_id,
        "proxy_port": jnp.where(est, 0, proxy_port),
        "rev_nat": jnp.where(svc_found, rev_nat, 0),
        # Encap selection lives in the node-ingress programs; carried
        # as zeros like the v4 pass so dual-stack callers share code.
        "tunnel_endpoint": jnp.zeros_like(dst_id),
        "established": est,
        "needs_ct_create": pass_ok & ~est,
    }


def apply_ct_creates6(ct: CtMap, out: dict, saddr_w, sport, proto) -> int:
    """Host-side follow-up for allowed new v6 flows (the v4 twin is
    pipeline.apply_ct_creates).  saddr_w is the 4-tuple of source word
    arrays the pipeline was called with.  Returns entries created."""
    need = np.asarray(out["needs_ct_create"])
    ndw = [np.asarray(w).view(np.uint32) for w in out["new_daddr_words"]]
    saw = [np.asarray(w).view(np.uint32) for w in saddr_w]
    np_ = np.asarray(out["new_dport"])
    ids = np.asarray(out["dst_identity"])
    rev = np.asarray(out["rev_nat"])
    sp = np.asarray(sport)
    pr = np.asarray(proto)

    def join(ws, i):
        addr = 0
        for w in range(4):
            addr = (addr << 32) | int(ws[w][i])
        return addr

    created = 0
    for i in np.flatnonzero(need):
        ct.create(
            CtKey6(
                daddr=join(ndw, i),
                saddr=join(saw, i),
                dport=int(np_[i]),
                sport=int(sp[i]),
                nexthdr=int(pr[i]),
            ),
            src_sec_id=int(ids[i]),
            rev_nat_index=int(rev[i]),
        )
        created += 1
    return created


def host_oracle6(
    ct: CtMap,
    lb: LbMap,
    ipcache: IpcacheMap,
    policy: PolicyMap,
    saddr: int,
    daddr: int,
    sport: int,
    dport: int,
    proto: int,
) -> dict:
    """Reference-semantics host walk (the v6 fuzz oracle)."""
    import ipaddress

    def i32w(addr: int):
        return tuple(
            np.int32(u32_to_i32(w)) for w in CtKey6.words(addr)
        )

    with np.errstate(over="ignore"):
        fh = int(
            flow_hash32_v6(
                i32w(saddr), i32w(daddr), np.int32(sport), np.int32(dport),
                np.int32(proto),
            )
        )
    be = lb.select_backend6(daddr, dport, fh)
    svc_found = be is not None
    new_daddr = be.target if svc_found else daddr
    new_dport = be.port if svc_found else dport
    rev = 0
    if svc_found:
        master = lb.lookup_service6(daddr, dport)
        rev = master.rev_nat_index if master else 0

    key = CtKey6(
        daddr=new_daddr, saddr=saddr, dport=new_dport, sport=sport,
        nexthdr=proto,
    )
    entry = ct.entries.get(key)
    est = entry is not None and entry.lifetime >= int(ct.clock())

    info = ipcache.lookup(str(ipaddress.IPv6Address(new_daddr)))
    dst_id = info.sec_label if info is not None else WORLD_ID

    allowed, proxy_port = policy.lookup(
        dst_id, new_dport, proto, direction=DIR_EGRESS, count_packets=False
    )
    pass_ok = est or allowed
    if not pass_ok:
        verdict = DROP
    elif proxy_port > 0 and not est:
        verdict = TO_PROXY
    else:
        verdict = FORWARD
    return {
        "verdict": verdict,
        "new_daddr": new_daddr,
        "new_dport": new_dport,
        "dst_identity": dst_id,
        "proxy_port": 0 if est else proxy_port,
        "rev_nat": rev if svc_found else 0,
        "established": est,
        "needs_ct_create": pass_ok and not est,
    }
