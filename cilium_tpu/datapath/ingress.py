"""Node-ingress datapath programs: netdev and overlay.

The batched analogs of the reference's physical-device and tunnel-device
tc programs:

- ``netdev_verdicts`` (reference: bpf/bpf_netdev.c:352 handle_ipv4):
  packets arriving from the world.  Source identity is derived from the
  ipcache LPM when the caller's identity is reserved (the HOST_ID
  override nuance included), destinations are demuxed against the local
  endpoint table (reference: bpf/lib/eps.h lookup_ip4_endpoint) —
  host endpoints pass to the stack, local endpoints run the ingress
  policy program, everything else is forwarded (via the overlay when
  the ipcache names a tunnel endpoint, reference:
  bpf_netdev.c encap_and_redirect_with_nodeid).

- ``overlay_verdicts`` (reference: bpf/bpf_overlay.c:97 handle_ipv4):
  packets decapped from the tunnel device.  The source identity comes
  from the tunnel key (VNI) verbatim — the reference trusts the encap
  peer — then the same local-delivery demux runs.

The per-endpoint ingress policy step fused into both passes is the
analog of the policy tail-call (reference: bpf/bpf_lxc.c:875,1008
tail_ipv6/ipv4_policy → bpf/lib/policy.h:127 policy_can_access_ingress):
CT-established packets skip policy; new flows run the {remote identity,
dport, proto, INGRESS} cascade with proxy redirection.  As in the
composed egress pipeline, one policy table (the destination endpoint's)
is passed per call — the runtime batches per endpoint exactly where the
kernel tail-calls into the per-endpoint program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..maps.ctmap import CtKey4, CtMap
from ..maps.ipcache import IpcacheMap
from ..maps.lxcmap import ENDPOINT_F_HOST, LxcMap
from ..maps.policymap import DIR_INGRESS, DevicePolicyMap, PolicyMap, policy_can_access_batch
from ..ops.lpm import DeviceLpm, lpm_lookup
from ..ops.maplookup import DeviceTable, exact_lookup
from .pipeline import DROP, FORWARD, TO_PROXY, WORLD_ID

# Additional routing outcomes at the node boundary.
TO_HOST = 3  # dst is the host endpoint: pass to the local stack (TC_ACT_OK)
TO_OVERLAY = 4  # encap to tunnel_endpoint (encap_and_redirect_with_nodeid)

HOST_ID = 1  # reserved host identity (pkg/identity/numericidentity.go)
RESERVED_ID_MAX = 255  # user identities start at 256 (numericidentity.go)


@jax.tree_util.register_pytree_node_class
@dataclass
class IngressTables:
    """Device snapshot of the maps the node-ingress programs read."""

    ipcache_id: DeviceLpm  # prefix -> sec_label
    ipcache_tun: DeviceLpm  # prefix -> tunnel_endpoint
    lxc: DeviceTable  # addr -> (lxc_id, flags)
    ct: DeviceTable  # (daddr, saddr, dport, sport, proto)
    policy: DevicePolicyMap  # the destination endpoint's policy

    def tree_flatten(self):
        return (
            (self.ipcache_id, self.ipcache_tun, self.lxc, self.ct, self.policy),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def build_ingress_tables(
    ipcache: IpcacheMap, lxc: LxcMap, ct: CtMap, policy: PolicyMap
) -> IngressTables:
    from .pipeline import pack_ct

    return IngressTables(
        ipcache_id=ipcache.to_device(),
        ipcache_tun=ipcache.to_device(value="tunnel_endpoint"),
        lxc=lxc.to_device(),
        ct=pack_ct(ct),
        policy=policy.to_device(),
    )


def _ingress_common(tables, src_id, saddr, daddr, sport, dport, proto):
    """Local-delivery demux + fused ingress policy program (shared by
    netdev and overlay once the source identity is resolved)."""
    # Local endpoint demux (eps.h lookup_ip4_endpoint).
    is_local, lxc_vals = exact_lookup(tables.lxc, daddr)
    lxc_id = lxc_vals[:, 0]
    is_host_ep = is_local & ((lxc_vals[:, 1] & ENDPOINT_F_HOST) != 0)

    # Ingress policy program for local (non-host) endpoints
    # (tail_ipv4_policy): CT established skips policy.  Two CT
    # orientations are live at node ingress (the reference's ct_lookup4
    # tries the tuple in both directions): the FORWARD orientation
    # matches entries this ingress pass created for earlier inbound
    # connections, and the REPLY orientation matches entries the egress
    # pipeline created when a local endpoint connected out — its key is
    # (daddr=remote, saddr=local), which the inbound reply packet
    # (saddr=remote, daddr=local) matches with saddr/daddr and
    # sport/dport swapped.
    est_fwd, _ = exact_lookup(tables.ct, daddr, saddr, dport, sport, proto)
    est_reply, _ = exact_lookup(tables.ct, saddr, daddr, sport, dport, proto)
    est = est_fwd | est_reply
    allowed, proxy_port = policy_can_access_batch(
        tables.policy, src_id, dport, proto, direction=DIR_INGRESS
    )
    pass_ok = est | allowed
    local_verdict = jnp.where(
        pass_ok,
        jnp.where((proxy_port > 0) & ~est, TO_PROXY, FORWARD),
        DROP,
    )

    # Non-local: overlay encap when the ipcache names a tunnel endpoint,
    # otherwise direct forward (bpf_netdev.c ENCAP_IFINDEX branch).
    tun_found, tunnel, _ = lpm_lookup(tables.ipcache_tun, daddr)
    remote_verdict = jnp.where(
        tun_found & (tunnel != 0), TO_OVERLAY, FORWARD
    )

    verdict = jnp.where(
        is_host_ep,
        TO_HOST,
        jnp.where(is_local, local_verdict, remote_verdict),
    )
    delivered = is_local & ~is_host_ep
    return {
        "verdict": verdict,
        "src_identity": src_id,
        "lxc_id": jnp.where(is_local, lxc_id, 0),
        "tunnel_endpoint": jnp.where(
            ~is_local & tun_found, tunnel, 0
        ),
        "proxy_port": jnp.where(delivered & ~est, proxy_port, 0),
        "established": est & delivered,
        # Allowed new inbound flows the host should record (reference:
        # ipv4_policy ct_create4 in the ingress tail call); the FORWARD
        # orientation (daddr=local endpoint) is the key to create.
        "needs_ct_create": delivered & pass_ok & ~est,
    }


@jax.jit
def netdev_verdicts(
    tables: IngressTables,
    saddr: jax.Array,
    daddr: jax.Array,
    sport: jax.Array,
    dport: jax.Array,
    proto: jax.Array,
    src_identity: jax.Array,
):
    """From-world node ingress (bpf_netdev.c:352 handle_ipv4)."""
    saddr = jnp.asarray(saddr, jnp.int32)
    daddr = jnp.asarray(daddr, jnp.int32)
    src_identity = jnp.asarray(src_identity, jnp.int32)

    # Reserved identities are refined by the ipcache source lookup —
    # except when the cache claims HOST_ID (SNAT makes world traffic
    # wear the host IP; trust the caller's identity then).
    reserved = (src_identity >= 0) & (src_identity <= RESERVED_ID_MAX)
    found, sec, _ = lpm_lookup(tables.ipcache_id, saddr)
    override = reserved & found & (sec != 0) & (sec != HOST_ID)
    src_id = jnp.where(
        override,
        sec,
        jnp.where(reserved & (src_identity == 0), WORLD_ID, src_identity),
    )
    return _ingress_common(
        tables, src_id, saddr, daddr,
        jnp.asarray(sport, jnp.int32), jnp.asarray(dport, jnp.int32),
        jnp.asarray(proto, jnp.int32),
    )


@jax.jit
def overlay_verdicts(
    tables: IngressTables,
    saddr: jax.Array,
    daddr: jax.Array,
    sport: jax.Array,
    dport: jax.Array,
    proto: jax.Array,
    tunnel_id: jax.Array,
):
    """Tunnel-device ingress (bpf_overlay.c:97 handle_ipv4): the VNI in
    the tunnel key IS the source identity."""
    return _ingress_common(
        tables,
        jnp.asarray(tunnel_id, jnp.int32),
        jnp.asarray(saddr, jnp.int32),
        jnp.asarray(daddr, jnp.int32),
        jnp.asarray(sport, jnp.int32),
        jnp.asarray(dport, jnp.int32),
        jnp.asarray(proto, jnp.int32),
    )


def host_oracle_netdev(
    ipcache: IpcacheMap,
    lxc: LxcMap,
    ct: CtMap,
    policy: PolicyMap,
    saddr: int,
    daddr: int,
    sport: int,
    dport: int,
    proto: int,
    src_identity: int = 0,
    tunnel_id: int | None = None,
) -> dict:
    """Reference-semantics host walk (the fuzz oracle).  With
    ``tunnel_id`` set this is the overlay program instead."""
    import ipaddress

    if tunnel_id is not None:
        src_id = tunnel_id
    else:
        src_id = src_identity
        if 0 <= src_id <= RESERVED_ID_MAX:
            info = ipcache.lookup(str(ipaddress.IPv4Address(saddr)))
            if (
                info is not None
                and info.sec_label
                and info.sec_label != HOST_ID
            ):
                src_id = info.sec_label
            elif src_id == 0:
                src_id = WORLD_ID

    ep = lxc.lookup_ip(str(ipaddress.IPv4Address(daddr)))
    out = {
        "src_identity": src_id,
        "lxc_id": ep.lxc_id if ep is not None else 0,
        "tunnel_endpoint": 0,
        "proxy_port": 0,
        "established": False,
        "needs_ct_create": False,
    }
    if ep is not None and ep.is_host:
        out["verdict"] = TO_HOST
        return out
    if ep is not None:
        now = int(ct.clock())

        def live(key):
            e = ct.entries.get(key)
            return e is not None and e.lifetime >= now

        est = live(
            CtKey4(daddr=daddr & 0xFFFFFFFF, saddr=saddr & 0xFFFFFFFF,
                   dport=dport, sport=sport, nexthdr=proto)
        ) or live(  # reply to an egress-created entry
            CtKey4(daddr=saddr & 0xFFFFFFFF, saddr=daddr & 0xFFFFFFFF,
                   dport=sport, sport=dport, nexthdr=proto)
        )
        allowed, proxy_port = policy.lookup(
            src_id, dport, proto, direction=DIR_INGRESS, count_packets=False
        )
        out["established"] = est
        if est or allowed:
            if proxy_port > 0 and not est:
                out["verdict"] = TO_PROXY
                out["proxy_port"] = proxy_port
            else:
                out["verdict"] = FORWARD
            out["needs_ct_create"] = not est
        else:
            out["verdict"] = DROP
        return out
    info = ipcache.lookup(str(ipaddress.IPv4Address(daddr)))
    if info is not None and info.tunnel_endpoint:
        out["verdict"] = TO_OVERLAY
        out["tunnel_endpoint"] = info.tunnel_endpoint
    else:
        out["verdict"] = FORWARD
    return out
