"""Datapath verdict accounting: metrics counters + monitor notifications
+ flow records.

The batched analog of the per-packet observability the kernel programs
emit inline (reference: bpf/lib/metrics.h update_metrics — every packet
counts into the {reason, direction} metrics map; bpf/lib/drop.h
send_drop_notify and trace.h send_trace_notify — perf-ring events the
monitor fans out; bpf/lib/policy_log.h send_policy_verdict_notify —
gated by the POLICY_VERDICT_NOTIFY option).  Here one numpy pass over a
composed-pipeline output dict accounts the whole batch, a BOUNDED
sample of drops is emitted as monitor events, allowed-verdict
POLICY-VERDICT events ride the (previously dead)
``OPTION_POLICY_VERDICT_NOTIFY`` runtime option, and the whole batch
lands in the flow-record ring as ONE columnar round (flowlog/ring.py)
— observability must not cost a per-packet host loop.
"""

from __future__ import annotations

import logging

import numpy as np

from ..maps.ctmap import CtMap
from ..maps.metricsmap import (
    METRIC_DIR_EGRESS,
    MetricsMap,
    REASON_FORWARDED,
)
from ..utils import flowdebug
from ..utils.option import OPTION_POLICY_VERDICT_NOTIFY
from .ingress import TO_HOST, TO_OVERLAY
from .pipeline import DROP, FORWARD, TO_PROXY

# Per-flow debug stream, flowdebug-gated (one boolean when disabled) —
# the datapath twin of pkg/flowdebug consumers in pkg/datapath.
_flow_log = logging.getLogger("cilium_tpu.datapath.flow")

# Metrics reasons are the NEGATED drop codes (reference: bpf_lxc.c
# send_drop_notify callers pass -ret into update_metrics).
DROP_POLICY_REASON = 133  # reference: common.h DROP_POLICY = -133

MAX_DROP_NOTIFICATIONS = 64  # per accounting pass (perf-ring analog cap)
MAX_VERDICT_NOTIFICATIONS = 64  # allowed-verdict events per pass


def account_verdicts(
    out: dict,
    metrics: MetricsMap,
    monitor=None,
    direction: int = METRIC_DIR_EGRESS,
    lengths=None,
    dports=None,
    proto=None,
    src_identity=None,
    flowlog=None,
    opts=None,
) -> dict:
    """Account one pipeline output batch.

    ``out`` is a datapath_verdicts/netdev_verdicts-style dict; packet
    byte ``lengths`` are optional (count-only accounting without them).
    ``flowlog`` receives the batch as ONE columnar flow-record round
    (path "datapath", match kind l3/l4, ct state from the pipeline's
    ``established`` column).  ``opts`` is the runtime OptionMap: with
    ``PolicyVerdictNotification`` enabled, a bounded sample of ALLOWED
    verdicts is published as POLICY-VERDICT monitor events alongside
    the existing drop sample (reference: send_policy_verdict_notify is
    compiled out unless the option is set).
    Returns {"forwarded": n, "dropped": n, "proxied": n}.
    """
    verdict = np.asarray(out["verdict"])
    nbytes = (
        np.asarray(lengths, np.int64)
        if lengths is not None
        else np.zeros(verdict.shape, np.int64)
    )
    # TO_HOST and TO_OVERLAY are delivery verdicts too (the reference
    # counts both as forwarded at the metrics map).
    fwd = (verdict == FORWARD) | (verdict == TO_HOST) | (verdict == TO_OVERLAY)
    drp = verdict == DROP
    prx = verdict == TO_PROXY
    n_fwd = int(fwd.sum())
    n_drp = int(drp.sum())
    n_prx = int(prx.sum())

    # Identity/port context shared by the drop sample, the verdict
    # sample, and the flow records.
    ids_dst = out.get("dst_identity")
    ids_src = out.get("src_identity")
    # The port the verdict was COMPUTED on: post-DNAT when the
    # pipeline did service translation.
    dp_arr = out.get("new_dport", dports)
    dp = np.asarray(dp_arr) if dp_arr is not None else None
    pr = np.asarray(proto) if proto is not None else None
    si = (
        np.asarray(src_identity) if src_identity is not None
        else (np.asarray(ids_src) if ids_src is not None else None)
    )
    di = np.asarray(ids_dst) if ids_dst is not None else None

    def ctx(i: int) -> tuple[int, int, int, int]:
        return (
            int(si[i]) if si is not None else 0,
            int(di[i]) if di is not None else 0,
            int(dp[i]) if dp is not None else 0,
            int(pr[i]) if pr is not None else 0,
        )

    if n_fwd or n_prx:
        # Proxy redirects still forward bytes (toward the proxy).
        metrics.update(
            REASON_FORWARDED, direction, count=n_fwd + n_prx,
            nbytes=int(nbytes[fwd | prx].sum()),
        )
        if (
            monitor is not None
            and opts is not None
            and opts.get(OPTION_POLICY_VERDICT_NOTIFY)
            and (
                flowlog is None
                or flowlog.monitor is None
                or flowlog.opts is None
            )
        ):
            # Allowed-verdict sample, option-gated: the reference only
            # emits policy-verdict events when the endpoint option is
            # set (policy_log.h POLICY_VERDICT_LOG_FILTER).  Skipped
            # when a monitor-wired flowlog is recording this batch —
            # its own POLICY-VERDICT fan-out covers it (emitting both
            # would double-count every allowed flow).
            ppt = out.get("proxy_port")
            pp = np.asarray(ppt) if ppt is not None else None
            for i in np.flatnonzero(fwd | prx)[:MAX_VERDICT_NOTIFICATIONS]:
                s, d, port, protonum = ctx(i)
                monitor.send_verdict(
                    src_identity=s, dst_identity=d, dport=port,
                    proto=protonum, allowed=True,
                    proxy_port=int(pp[i]) if pp is not None else 0,
                )
    if n_drp:
        metrics.update(
            DROP_POLICY_REASON, direction, count=n_drp,
            nbytes=int(nbytes[drp].sum()),
        )
        if monitor is not None:
            for i in np.flatnonzero(drp)[:MAX_DROP_NOTIFICATIONS]:
                s, d, port, protonum = ctx(i)
                monitor.send_verdict(
                    src_identity=s, dst_identity=d, dport=port,
                    proto=protonum, allowed=False,
                )
                flowdebug.log(
                    _flow_log,
                    "datapath drop: identity %d -> %d dport %d proto %d",
                    s, d, port, protonum,
                )
    if flowlog is not None and len(verdict):
        _record_batch(flowlog, out, verdict, fwd | prx, drp, si, di, dp, pr)
    return {"forwarded": n_fwd, "dropped": n_drp, "proxied": n_prx}


def _record_batch(flowlog, out, verdict, allowed, dropped,
                  si, di, dp, pr) -> None:
    """One columnar flow-record round for the whole batch.  Packet-
    layer verdicts have no L7 rule row: rule_id is -1 and the match
    kind column says which layer decided (l4 when a port policy was
    consulted, l3 otherwise)."""
    from ..flowlog import (
        CODE_DENIED,
        CODE_FORWARDED,
        MATCH_L3,
        MATCH_L4,
        PATH_DATAPATH,
    )

    sel = allowed | dropped
    idx = np.flatnonzero(sel)
    if not len(idx):
        return
    n = len(idx)
    codes = np.where(dropped[idx], CODE_DENIED, CODE_FORWARDED).astype(np.int8)
    kind = MATCH_L4 if dp is not None else MATCH_L3
    cols = {
        "match_kind": [kind] * n,
        "drop_reason": np.where(
            dropped[idx], DROP_POLICY_REASON, 0
        ).astype(np.int32),
    }
    if si is not None:
        cols["src_identity"] = si[idx]
    if di is not None:
        cols["dst_identity"] = di[idx]
    if dp is not None:
        cols["dport"] = dp[idx]
    if pr is not None:
        cols["proto"] = pr[idx]
    est = out.get("established")
    if est is not None:
        cols["ct_state"] = CtMap.state_codes(np.asarray(est)[idx])
    flowlog.add_round(
        PATH_DATAPATH,
        idx.astype(np.int64),  # batch row index stands in for a conn id
        codes,
        cols=cols,
    )
