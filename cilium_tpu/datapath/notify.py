"""Datapath verdict accounting: metrics counters + monitor notifications.

The batched analog of the per-packet observability the kernel programs
emit inline (reference: bpf/lib/metrics.h update_metrics — every packet
counts into the {reason, direction} metrics map; bpf/lib/drop.h
send_drop_notify and trace.h send_trace_notify — perf-ring events the
monitor fans out).  Here one numpy pass over a composed-pipeline output
dict accounts the whole batch, and a BOUNDED sample of drops is emitted
as monitor events (the reference rate-limits notifications at the
perf-ring boundary for the same reason: observability must not cost a
per-packet host loop).
"""

from __future__ import annotations

import numpy as np

from ..maps.metricsmap import (
    METRIC_DIR_EGRESS,
    MetricsMap,
    REASON_FORWARDED,
)
from .ingress import TO_HOST, TO_OVERLAY
from .pipeline import DROP, FORWARD, TO_PROXY

# Metrics reasons are the NEGATED drop codes (reference: bpf_lxc.c
# send_drop_notify callers pass -ret into update_metrics).
DROP_POLICY_REASON = 133  # reference: common.h DROP_POLICY = -133

MAX_DROP_NOTIFICATIONS = 64  # per accounting pass (perf-ring analog cap)


def account_verdicts(
    out: dict,
    metrics: MetricsMap,
    monitor=None,
    direction: int = METRIC_DIR_EGRESS,
    lengths=None,
    dports=None,
    proto=None,
    src_identity=None,
) -> dict:
    """Account one pipeline output batch.

    ``out`` is a datapath_verdicts/netdev_verdicts-style dict; packet
    byte ``lengths`` are optional (count-only accounting without them).
    Returns {"forwarded": n, "dropped": n, "proxied": n}.
    """
    verdict = np.asarray(out["verdict"])
    nbytes = (
        np.asarray(lengths, np.int64)
        if lengths is not None
        else np.zeros(verdict.shape, np.int64)
    )
    # TO_HOST and TO_OVERLAY are delivery verdicts too (the reference
    # counts both as forwarded at the metrics map).
    fwd = (verdict == FORWARD) | (verdict == TO_HOST) | (verdict == TO_OVERLAY)
    drp = verdict == DROP
    prx = verdict == TO_PROXY
    n_fwd = int(fwd.sum())
    n_drp = int(drp.sum())
    n_prx = int(prx.sum())
    if n_fwd or n_prx:
        # Proxy redirects still forward bytes (toward the proxy).
        metrics.update(
            REASON_FORWARDED, direction, count=n_fwd + n_prx,
            nbytes=int(nbytes[fwd | prx].sum()),
        )
    if n_drp:
        metrics.update(
            DROP_POLICY_REASON, direction, count=n_drp,
            nbytes=int(nbytes[drp].sum()),
        )
        if monitor is not None:
            # Identity context: the egress pipeline carries the
            # destination identity; the ingress programs carry the
            # (remote) source identity instead.
            ids_dst = out.get("dst_identity")
            ids_src = out.get("src_identity")
            # The port the verdict was COMPUTED on: post-DNAT when the
            # pipeline did service translation.
            dp_arr = out.get("new_dport", dports)
            dp = np.asarray(dp_arr) if dp_arr is not None else None
            pr = np.asarray(proto) if proto is not None else None
            si = (
                np.asarray(src_identity) if src_identity is not None
                else (np.asarray(ids_src) if ids_src is not None else None)
            )
            di = np.asarray(ids_dst) if ids_dst is not None else None
            for i in np.flatnonzero(drp)[:MAX_DROP_NOTIFICATIONS]:
                monitor.send_verdict(
                    src_identity=int(si[i]) if si is not None else 0,
                    dst_identity=int(di[i]) if di is not None else 0,
                    dport=int(dp[i]) if dp is not None else 0,
                    proto=int(pr[i]) if pr is not None else 0,
                    allowed=False,
                )
    return {"forwarded": n_fwd, "dropped": n_drp, "proxied": n_prx}
