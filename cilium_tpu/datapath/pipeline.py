"""Composed L3/L4 datapath step: CT -> LB -> ipcache -> policy -> verdict.

The batched analog of the reference's per-packet egress pipeline
(reference: bpf/bpf_lxc.c:684-760 handle_ipv4_from_lxc): one jitted
device pass takes [F] packet 5-tuples and renders, for every packet,

  1. service translation  — lb4 service match + backend select
     (reference: bpf/lib/lb.h:604 lb4_lookup_service, :158 slave pick);
     DNAT daddr/dport to the chosen backend
  2. conntrack lookup     — established 5-tuples (post-DNAT, matching
     lb4_local before ct_create4) skip policy
     (reference: bpf/lib/conntrack.h ct_lookup4)
  3. destination identity — ipcache LPM on the (DNATed) daddr
     (reference: bpf/lib/eps.h lookup_ip4_remote_endpoint)
  4. policy               — {identity, dport, proto, dir} cascade
     (reference: bpf/lib/policy.h:47 __policy_can_access)
  5. verdict              — FORWARD / DROP / PROXY-redirect, plus the
     host-side actions the kernel path would do inline: needs_ct_create
     for allowed new flows (ct_create4) and the tunnel endpoint for
     encap (reference: bpf/lib/encap.h).

Everything is a fused [F, N] compare/reduce on device — no per-packet
host work; the host applies CT creates from the returned flags (the
device is a pure function of the table snapshot, mirroring how the
kernel path reads pinned maps).  Bit-exactness against the host maps
is fuzz-checked in tests/test_datapath_pipeline.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..maps.ctmap import CtKey4, CtMap
from ..maps.lbmap import DeviceLbMap, LbMap, lb4_select_backend_batch
from ..maps.ipcache import IpcacheMap
from ..maps.policymap import (
    DIR_EGRESS,
    DevicePolicyMap,
    PolicyMap,
    policy_can_access_batch,
)
from ..ops.lpm import DeviceLpm, lpm_lookup
from ..ops.maplookup import DeviceTable, exact_lookup, pack_table, u32_to_i32

# Verdicts (the reference's TC return codes collapse to these three
# outcomes at this layer; DROP carries the policy-denied drop reason,
# reference: bpf/lib/drop.h DROP_POLICY).
FORWARD = 0
DROP = 1
TO_PROXY = 2

WORLD_ID = 2  # reserved world identity (pkg/identity/numericidentity.go)


def flow_hash32(saddr, daddr, sport, dport, proto):
    """Deterministic per-flow hash used for backend selection; identical
    arithmetic on host (numpy) and device (jnp) so both pick the same
    backend (the kernel uses skb->hash; any fixed function works as long
    as every layer agrees)."""
    h = (
        saddr * np.int32(-1640531527)  # 0x9E3779B9 as int32
        + daddr * np.int32(40503)
        + sport * np.int32(31)
        + dport * np.int32(131)
        + proto
    )
    return h


@jax.tree_util.register_pytree_node_class
@dataclass
class DatapathTables:
    """One device-resident snapshot of the maps the pipeline reads."""

    ct: DeviceTable  # cols (daddr, saddr, dport, sport, proto)
    lb: DeviceLbMap
    ipcache: DeviceLpm
    policy: DevicePolicyMap

    def tree_flatten(self):
        return ((self.ct, self.lb, self.ipcache, self.policy), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def pack_ct(ct: CtMap) -> DeviceTable:
    """Snapshot live CT entries into a device exact-match table.

    Expired-but-not-yet-GCed entries must NOT reach the device table:
    ct_lookup4 treats them as misses (conntrack.h lifetime check), so
    the snapshot filters on lifetime like CtMap.lookup does."""
    now = int(ct.clock())
    live = [
        k for k, e in ct.entries.items()
        if e.lifetime >= now and isinstance(k, CtKey4)
    ]
    keys = np.zeros((len(live), 5), np.int64)
    for i, k in enumerate(live):
        keys[i] = (k.daddr, k.saddr, k.dport, k.sport, k.nexthdr)
    keys = u32_to_i32(keys)
    vals = np.zeros((len(live), 1), np.int64)
    return pack_table(keys, vals)


def build_tables(
    ct: CtMap, lb: LbMap, ipcache: IpcacheMap, policy: PolicyMap
) -> DatapathTables:
    """Snapshot host maps into device tables (the analog of the pinned
    BPF maps the kernel programs read)."""
    return DatapathTables(
        ct=pack_ct(ct),
        lb=lb.to_device(),
        ipcache=ipcache.to_device(),
        policy=policy.to_device(),
    )


@jax.jit
def datapath_verdicts(
    tables: DatapathTables,
    saddr: jax.Array,  # [F] int32 (uint32 bit pattern)
    daddr: jax.Array,  # [F] int32
    sport: jax.Array,  # [F] int32
    dport: jax.Array,  # [F] int32
    proto: jax.Array,  # [F] int32
):
    """One composed device pass; returns a dict of [F] arrays:

    verdict (FORWARD/DROP/TO_PROXY), new_daddr, new_dport (post-DNAT),
    dst_identity, proxy_port, rev_nat, tunnel_endpoint, established,
    needs_ct_create (allowed new flows the host should ct_create4).
    """
    saddr = jnp.asarray(saddr, jnp.int32)
    daddr = jnp.asarray(daddr, jnp.int32)
    sport = jnp.asarray(sport, jnp.int32)
    dport = jnp.asarray(dport, jnp.int32)
    proto = jnp.asarray(proto, jnp.int32)

    # 1. Service translation (reference: lb.h:604, lxc egress does the
    # service lookup before conntrack create so CT tracks the backend
    # tuple).
    fh = flow_hash32(saddr, daddr, sport, dport, proto)
    svc_found, be_addr, be_port, rev_nat = lb4_select_backend_batch(
        tables.lb, daddr, dport, fh
    )
    new_daddr = jnp.where(svc_found, be_addr, daddr)
    new_dport = jnp.where(svc_found, be_port, dport)

    # 2. Conntrack on the post-DNAT tuple.
    est, _ = exact_lookup(
        tables.ct, new_daddr, saddr, new_dport, sport, proto
    )

    # 3. Destination identity from the ipcache LPM; unknown -> world
    # (reference: eps.h lookup falls back to WORLD_ID for misses).
    ip_found, ident, _plen = lpm_lookup(tables.ipcache, new_daddr)
    dst_id = jnp.where(ip_found, ident, jnp.int32(WORLD_ID))
    # Egress encap selection lives in the node-ingress programs
    # (datapath/ingress.py netdev_verdicts reads the tunnel column);
    # this endpoint-egress pass carries 0 here.
    tunnel = jnp.zeros_like(dst_id)

    # 4. Policy cascade on new connections (established flows were
    # admitted at connect time — reference: handle_ipv4 CT_ESTABLISHED
    # path skips policy).
    allowed, proxy_port = policy_can_access_batch(
        tables.policy, dst_id, new_dport, proto, direction=DIR_EGRESS
    )

    pass_ok = est | allowed
    verdict = jnp.where(
        pass_ok,
        jnp.where((proxy_port > 0) & ~est, TO_PROXY, FORWARD),
        DROP,
    )
    needs_ct_create = pass_ok & ~est
    return {
        "verdict": verdict,
        "new_daddr": new_daddr,
        "new_dport": new_dport,
        "dst_identity": dst_id,
        "proxy_port": jnp.where(est, 0, proxy_port),
        "rev_nat": jnp.where(svc_found, rev_nat, 0),
        "tunnel_endpoint": tunnel,
        "established": est,
        "needs_ct_create": needs_ct_create,
    }


def apply_ct_creates(ct: CtMap, out: dict, saddr, sport, proto) -> int:
    """Host-side follow-up: create CT entries for allowed new flows
    (reference: conntrack.h ct_create4 after the policy verdict).
    Returns the number of entries created."""
    need = np.asarray(out["needs_ct_create"])
    nd = np.asarray(out["new_daddr"]).view(np.uint32)
    np_ = np.asarray(out["new_dport"])
    ids = np.asarray(out["dst_identity"])
    rev = np.asarray(out["rev_nat"])
    sa = np.asarray(saddr).view(np.uint32)
    sp = np.asarray(sport)
    pr = np.asarray(proto)
    created = 0
    for i in np.flatnonzero(need):
        ct.create(
            CtKey4(
                daddr=int(nd[i]),
                saddr=int(sa[i]),
                dport=int(np_[i]),
                sport=int(sp[i]),
                nexthdr=int(pr[i]),
            ),
            src_sec_id=int(ids[i]),
            rev_nat_index=int(rev[i]),
        )
        created += 1
    return created


def host_oracle(
    ct: CtMap,
    lb: LbMap,
    ipcache: IpcacheMap,
    policy: PolicyMap,
    saddr: int,
    daddr: int,
    sport: int,
    dport: int,
    proto: int,
) -> dict:
    """Reference-semantics host walk of the same pipeline (the fuzz
    oracle; pure read — no CT refresh / counters)."""
    import ipaddress

    def i32(v: int) -> np.int32:
        return u32_to_i32(v).astype(np.int32)

    with np.errstate(over="ignore"):
        fh = int(
            flow_hash32(i32(saddr), i32(daddr), i32(sport), i32(dport),
                        i32(proto))
        )
    be = lb.select_backend(daddr & 0xFFFFFFFF, dport, fh)
    svc_found = be is not None
    new_daddr = be.target if svc_found else daddr & 0xFFFFFFFF
    new_dport = be.port if svc_found else dport
    rev = 0
    if svc_found:
        master = lb.lookup_service(daddr & 0xFFFFFFFF, dport)
        rev = master.rev_nat_index if master else 0

    key = CtKey4(
        daddr=new_daddr, saddr=saddr & 0xFFFFFFFF, dport=new_dport,
        sport=sport, nexthdr=proto,
    )
    entry = ct.entries.get(key)
    est = entry is not None and entry.lifetime >= int(ct.clock())

    info = ipcache.lookup(str(ipaddress.IPv4Address(new_daddr)))
    dst_id = info.sec_label if info is not None else WORLD_ID

    allowed, proxy_port = policy.lookup(
        dst_id, new_dport, proto, direction=DIR_EGRESS, count_packets=False
    )
    pass_ok = est or allowed
    if not pass_ok:
        verdict = DROP
    elif proxy_port > 0 and not est:
        verdict = TO_PROXY
    else:
        verdict = FORWARD
    return {
        "verdict": verdict,
        "new_daddr": new_daddr,
        "new_dport": new_dport,
        "dst_identity": dst_id,
        "proxy_port": 0 if est else proxy_port,
        "rev_nat": rev if svc_found else 0,
        "established": est,
        "needs_ct_create": pass_ok and not est,
    }
