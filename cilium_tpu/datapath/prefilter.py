"""XDP prefilter analog: revisioned CIDR deny-lists compiled to device LPM.

reference: pkg/datapath/prefilter/prefilter.go — a pair of maps per
protocol (v4/v6), Insert/Delete guarded by a revision counter so
concurrent updates from stale readers are rejected; the datapath drops any
packet whose source address matches (bpf/bpf_xdp.c check_v4).
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Optional

from ..ops.lpm import DeviceLpm, build_lpm


class PreFilter:
    """reference: prefilter.go:125 Insert / :162 Delete."""

    def __init__(self) -> None:
        self.revision = 1
        self._v4: set[str] = set()
        self._v6: set[str] = set()
        self._mutex = threading.RLock()
        self._device_v4: Optional[DeviceLpm] = None
        self._device_v6: Optional[DeviceLpm] = None
        self._dirty = True

    def insert(self, revision: int, cidrs: list[str]) -> int:
        """Returns the new revision; raises on stale revision
        (reference: prefilter.go revision check)."""
        with self._mutex:
            if revision != self.revision:
                raise ValueError(
                    f"stale prefilter revision {revision} != {self.revision}"
                )
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                (self._v4 if net.version == 4 else self._v6).add(str(net))
            self.revision += 1
            self._dirty = True
            return self.revision

    def delete(self, revision: int, cidrs: list[str]) -> int:
        with self._mutex:
            if revision != self.revision:
                raise ValueError(
                    f"stale prefilter revision {revision} != {self.revision}"
                )
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                target = self._v4 if net.version == 4 else self._v6
                if str(net) not in target:
                    raise KeyError(f"CIDR {net} not in prefilter")
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                (self._v4 if net.version == 4 else self._v6).discard(str(net))
            self.revision += 1
            self._dirty = True
            return self.revision

    def dump(self) -> tuple[int, list[str]]:
        """reference: prefilter.go Dump — (revision, cidrs)."""
        with self._mutex:
            return self.revision, sorted(self._v4) + sorted(self._v6)

    def device_lpm(self, v6: bool = False) -> DeviceLpm:
        """Compile (cached until dirty) the deny-list to the device LPM."""
        with self._mutex:
            if self._dirty:
                self._device_v4 = build_lpm(
                    [(c, 1) for c in sorted(self._v4)], v6=False
                )
                self._device_v6 = build_lpm(
                    [(c, 1) for c in sorted(self._v6)], v6=True
                )
                self._dirty = False
            return self._device_v6 if v6 else self._device_v4
