"""XDP prefilter analog: revisioned CIDR deny-lists compiled to device LPM.

reference: pkg/datapath/prefilter/prefilter.go — a pair of maps per
protocol (v4/v6), Insert/Delete guarded by a revision counter so
concurrent updates from stale readers are rejected; the datapath drops any
packet whose source address matches (bpf/bpf_xdp.c check_v4).
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Optional

import numpy as np

from ..ops.lpm import DeviceLpm, build_lpm, lpm_lookup


class PreFilter:
    """reference: prefilter.go:125 Insert / :162 Delete."""

    def __init__(self) -> None:
        self.revision = 1
        self._v4: set[str] = set()
        self._v6: set[str] = set()
        self._mutex = threading.RLock()
        self._device_v4: Optional[DeviceLpm] = None
        self._device_v6: Optional[DeviceLpm] = None
        self._dirty = True

    def insert(self, revision: int, cidrs: list[str]) -> int:
        """Returns the new revision; raises on stale revision
        (reference: prefilter.go revision check)."""
        with self._mutex:
            if revision != self.revision:
                raise ValueError(
                    f"stale prefilter revision {revision} != {self.revision}"
                )
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                (self._v4 if net.version == 4 else self._v6).add(str(net))
            self.revision += 1
            self._dirty = True
            return self.revision

    def delete(self, revision: int, cidrs: list[str]) -> int:
        with self._mutex:
            if revision != self.revision:
                raise ValueError(
                    f"stale prefilter revision {revision} != {self.revision}"
                )
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                target = self._v4 if net.version == 4 else self._v6
                if str(net) not in target:
                    raise KeyError(f"CIDR {net} not in prefilter")
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                (self._v4 if net.version == 4 else self._v6).discard(str(net))
            self.revision += 1
            self._dirty = True
            return self.revision

    def dump(self) -> tuple[int, list[str]]:
        """reference: prefilter.go Dump — (revision, cidrs)."""
        with self._mutex:
            return self.revision, sorted(self._v4) + sorted(self._v6)

    def device_lpm(self, v6: bool = False) -> DeviceLpm:
        """Compile (cached until dirty) the deny-list to the device LPM."""
        with self._mutex:
            if self._dirty:
                self._device_v4 = build_lpm(
                    [(c, 1) for c in sorted(self._v4)], v6=False
                )
                self._device_v6 = build_lpm(
                    [(c, 1) for c in sorted(self._v6)], v6=True
                )
                self._dirty = False
            return self._device_v6 if v6 else self._device_v4

    def filter_batch(self, saddr, v6: bool = False, flowlog=None,
                     monitor=None) -> np.ndarray:
        """XDP source-drop pass over a batch (reference: bpf_xdp.c
        check_v4 — drop any packet whose source matches the deny LPM).
        ``saddr``: for v4 one [F] int32 word array, for v6 the four
        word arrays stacked [4, F].  Returns the [F] bool KEEP mask.

        Observability per BATCH, not per packet: drops land in the
        flow-record ring as one columnar round (path "xdp", match kind
        l3) and a bounded sample fans out as monitor drop events."""
        lpm = self.device_lpm(v6)
        words = (
            [np.asarray(saddr[w]) for w in range(4)] if v6
            else [np.asarray(saddr)]
        )
        found, _value, _plen = lpm_lookup(lpm, *words)
        dropped = np.asarray(found)
        keep = ~dropped
        idx = np.flatnonzero(dropped)
        if len(idx) and monitor is not None:
            for i in idx[:64]:  # perf-ring analog cap
                monitor.send_verdict(
                    src_identity=0, dst_identity=0, dport=0, proto=0,
                    allowed=False,
                )
        if len(idx) and flowlog is not None:
            from ..flowlog import CODE_DENIED, MATCH_L3, PATH_XDP

            cols = {
                "match_kind": [MATCH_L3] * len(idx),
                "src_addr_word": words[0][idx].astype(np.int64),
            }
            flowlog.add_round(
                PATH_XDP,
                idx.astype(np.int64),  # batch row index as flow handle
                np.full(len(idx), CODE_DENIED, np.int8),
                reason="prefilter",
                cols=cols,
            )
        return keep
