"""Columnar wire protocol for the verdict-service seam.

The ABI analog of the reference's cgo surface (reference:
proxylib/libcilium.h — OpenModule/OnNewConnection/OnData/Close) recast as
a message protocol over a unix SOCK_STREAM socket, so the datapath shim
and the verdict service can live in different processes (the reference's
Envoy ⇄ libcilium.so boundary).

Design choices, TPU-first:

- **Columnar batches.** A DATA batch carries parallel arrays
  (conn_ids[], flags[], lengths[]) plus one concatenated byte blob, so
  the service can lift a whole batch into device-ready numpy arrays with
  O(1) vectorized ops instead of per-entry parsing.  Same for verdict
  batches (results[], op_counts[], flat FilterOp array, inject blob).
- **FilterOp layout** is bit-identical to the reference ABI struct
  ``{uint64 op; int64 n_bytes}`` (reference: proxylib/proxylib/types.h)
  so the C++ shim shares the struct with the reference's consumer.
- **≤16 ops per verdict entry** — the OnIO contract's op capacity
  (reference: envoy/cilium_proxylib.cc:199 ``max_ops = 16``).  The
  service splits longer op lists into continuation entries for the same
  connection, preserving order.

All integers are little-endian.  Frame: ``magic u16, type u16, len u32``
then ``len`` payload bytes.
"""

from __future__ import annotations

import socket
import struct
import time
from dataclasses import dataclass, field

import numpy as np

MAGIC = 0xC17A
HEADER = struct.Struct("<HHI")

# Message types
MSG_OPEN_MODULE = 1
MSG_MODULE_ID = 2
MSG_NEW_CONNECTION = 3
MSG_CONN_RESULT = 4
MSG_DATA_BATCH = 5
MSG_VERDICT_BATCH = 6
MSG_CLOSE = 7
MSG_POLICY_UPDATE = 8
MSG_ACK = 9
# Fixed-width variant of DATA_BATCH: entries are pre-padded rows of one
# width, so the service reshapes the payload straight into the device
# batch layout (request direction only, no end_stream).  The TPU-first
# ingestion format: padding happens at the edge, once.
MSG_DATA_MATRIX = 10
MSG_STATUS = 11  # -> MSG_STATUS_REPLY (JSON service counters)
MSG_STATUS_REPLY = 12
# One reply frame covering MULTIPLE data-batch seqs (a whole aggregated
# round): {m, seqs u64[m], entry_counts u32[m]} + one verdict-batch body
# over all entries.  Sent only to clients that speak the matrix format
# (the C++ shim uses DATA_BATCH/VERDICT_BATCH and never sees this).
MSG_VERDICT_MULTI = 13
# DATA_BATCH with a deadline budget prepended: {deadline_us u32} + the
# standard DATA_BATCH payload.  The budget is RELATIVE (microseconds of
# remaining patience at send time) so no clock sync is needed; the
# service anchors it to its own monotonic clock at receive.  Entries
# whose deadline passes while queued are shed with a typed SHED verdict
# — the fail-closed alternative to a silent queue hang.  Old clients
# (incl. the native shim) keep sending plain DATA_BATCH.
MSG_DATA_BATCH_DL = 14
# Latency-trace dump: request carries optional JSON
# ``{"n": <max spans>, "kind": "sample"|"slow"|"shed"}``; the reply is
# JSON ``{"spans": [...], "latency": {...}}`` from the service's
# verdict tracer (sidecar/trace.py) — the wire surface behind
# `cilium sidecar trace`.
MSG_TRACE = 15
MSG_TRACE_REPLY = 16
# Flow-record query: request carries optional JSON filters
# ``{"n": <max records>, "verdict": "Forwarded"|"Denied"|"Shed"|
# "Error", "path": "vec"|"oracle"|"host"|"shed", "rule": <rule id>,
# "conn": <conn id>, "since": <record seq cursor>}``; the reply is
# JSON ``{"records": [...], "stats": {...}}`` from the service's flow
# log (flowlog/ring.py) — the wire surface behind `cilium observe`.
MSG_OBSERVE = 17
MSG_OBSERVE_REPLY = 18
# Shared-memory transport negotiation + notification (sidecar/shm.py).
# ATTACH carries JSON ``{"generation": u32, "data": <segment name>,
# "verdict": <segment name>}``; the service validates magic/generation
# against the segment headers and replies ATTACH_REPLY JSON
# ``{"status": FilterResult, "generation": u32, "error": str}``.  The
# socket remains the control channel and fail-closed fallback rung;
# after a successful attach, data batches ride the data ring and
# verdict frames the verdict ring, with DOORBELL (shim→service) and
# CREDIT (service→shim) frames batching the wakeups.  A CREDIT with
# the quarantined flag demotes the session to the socket transport.
MSG_SHM_ATTACH = 19
MSG_SHM_ATTACH_REPLY = 20
MSG_SHM_DOORBELL = 21
MSG_SHM_CREDIT = 22
MSG_SHM_DETACH = 23  # -> MSG_ACK; client tears its rings down after
# Established-flow verdict cache (service <-> shim).  ENABLE is the
# client's one-time opt-in (fire-and-forget, no reply): a service never
# sends cache frames to a shim that did not announce support, so the
# native shim's fixed dispatch table stays untouched.  GRANT
# (service→shim) arms one conn: the claimed verdict/rule is
# byte-invariant for the flow's remainder under the carried epoch, and
# the shim may short-circuit frame-aligned request pushes locally
# (bytes never cross the transport).  REVOKE (service→shim) carries the
# NEW committed epoch: every grant under an older epoch is dead (sent
# to each opted-in session BEFORE the epoch pointer-flip commits).
MSG_CACHE_ENABLE = 24
MSG_CACHE_GRANT = 25
MSG_CACHE_REVOKE = 26

# Multi-tenant fan-in: the shim announces its session identity (the
# pod/workload name admission quotas and shed/quarantine metrics key
# on) right after connect and again on every reconnect replay.
# Fire-and-forget (no reply) so a legacy peer — including the bench
# null server — just ignores it; an unnamed session quotas under a
# synthetic per-session identity.  JSON payload: {"identity": str}.
MSG_SESSION_HELLO = 27

# Hitless restart handoff (Envoy hot-restart analog, over the same
# unix socket as everything else): a SUCCESSOR service dials its
# predecessor's socket and sends MSG_HANDOFF with its own restart
# generation.  The predecessor serializes its warm state (sessions,
# conn tables, armed grants, policy epoch + rule sources) into the
# versioned snapshot of snapshot_handoff(), FENCES itself — from that
# instant every late control write is rejected typed and every late
# data frame sheds typed (PR 1 kvstore fencing semantics: the zombie
# must never answer as if it were still primary) — releases the
# listening socket path, and replies MSG_HANDOFF_REPLY carrying the
# snapshot JSON.  A predecessor too old to speak the protocol simply
# drops the unknown message; the successor times out and boots cold
# (the kill -9 path), which is always correct, just not warm.
MSG_HANDOFF = 28
MSG_HANDOFF_REPLY = 29

# Flight-recorder timeline (sidecar/blackbox.py): the client asks for
# the incident timeline — declared-edge events, occupancy buckets and
# postmortem summaries — with JSON request filters {"n", "since",
# "table"}; the reply is the recorder's dump() as JSON.  Same
# request/reply control shape as MSG_TRACE.
MSG_TIMELINE = 30
MSG_TIMELINE_REPLY = 31

# Device-economics ledger (sidecar/ledger.py): the client asks for the
# compile ledger and batch-formation provenance — per-cause compile
# events, per-trigger round formation stats, resident-executable
# census — with JSON request filters {"n", "since", "cause"}; the
# reply is the ledger's dump() as JSON.  Same request/reply control
# shape as MSG_TIMELINE.
MSG_LEDGER = 32
MSG_LEDGER_REPLY = 33

# Conn-registration flags (optional trailing byte on
# MSG_NEW_CONNECTION; absent = 0, so old shims interop unchanged).
# RETAINED rides the session-replay re-registration: the shim still
# holds this conn's retained-buffer mirror bytes from before the
# restart (no round failed typed on it), so the successor may adopt
# the predecessor's flow-buffer residue — both sides then resume the
# SAME mid-frame parse state and a frame split across the restart
# reassembles.  Without the flag the shim has dropped its copy
# (fail-closed), and adopting service-side residue would desync the
# op stream from the shim's buffer: the service must discard it.
CONN_FLAG_RETAINED = 1

# Conn-result flags (optional trailing u4 on MSG_CONN_RESULT; absent
# = 0).  RESIDUE_ADOPTED answers RETAINED: the successor installed
# the predecessor's mid-frame residue for this conn, so the shim must
# KEEP its retained buffer and overshoot counters through the replay
# instead of resetting fail-closed — the service mirror matches them
# byte for byte.
CONN_RESULT_FLAG_RESIDUE_ADOPTED = 1

# OnIO op capacity per verdict entry (reference: cilium_proxylib.cc:199).
MAX_OPS_PER_ENTRY = 16

FILTER_OP = np.dtype([("op", "<u8"), ("n_bytes", "<i8")])

# flags bits in a DATA batch entry
FLAG_REPLY = 1
FLAG_END_STREAM = 2

# flags bits in a DATA_MATRIX header: the datapath edge (which built the
# rows and owns frame reassembly) declares every row is exactly one
# complete frame, letting the service skip the per-row content scan on
# its vectorized path.  Same trust domain as the byte accounting the
# shim already owns (reference: the Envoy-side filter decides framing
# before calling OnData, cilium_proxylib.cc:125).
MAT_FLAG_COMPLETE = 1


class WireError(Exception):
    pass


class ConnectionClosed(WireError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosed("peer closed")
        buf += chunk
    return bytes(buf)


def send_msg(sock: socket.socket, msg_type: int, payload: bytes) -> None:
    sock.sendall(HEADER.pack(MAGIC, msg_type, len(payload)) + payload)


def recv_msg(sock: socket.socket) -> tuple[int, bytes]:
    magic, msg_type, length = HEADER.unpack(_recv_exact(sock, HEADER.size))
    if magic != MAGIC:
        raise WireError(f"bad magic {magic:#x}")
    return msg_type, _recv_exact(sock, length) if length else b""


class BufferedReader:
    """Frame reader with one kernel recv per wakeup instead of two
    syscalls per message — and a free backlog signal: bytes left in the
    buffer after a frame means more messages are already waiting (the
    service's cut-through/aggregate decision reads this instead of
    paying a select() per message)."""

    __slots__ = ("sock", "buf", "off")

    RECV_CHUNK = 1 << 18

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = bytearray()
        self.off = 0

    @property
    def pending(self) -> bool:
        """A complete or partial further frame is already buffered."""
        return len(self.buf) - self.off > 0

    def _fill(self) -> None:
        chunk = self.sock.recv(self.RECV_CHUNK)
        if not chunk:
            raise ConnectionClosed("peer closed")
        if self.off and self.off == len(self.buf):
            self.buf = bytearray(chunk)
            self.off = 0
        else:
            self.buf += chunk

    def recv_msg(self) -> tuple[int, bytes]:
        hs = HEADER.size
        while True:
            avail = len(self.buf) - self.off
            if avail >= hs:
                magic, msg_type, length = HEADER.unpack_from(self.buf, self.off)
                if magic != MAGIC:
                    raise WireError(f"bad magic {magic:#x}")
                if avail >= hs + length:
                    start = self.off + hs
                    payload = bytes(self.buf[start : start + length])
                    self.off = start + length
                    # Compact once everything is consumed (cheap reset)
                    # or when the dead prefix grows large.
                    if self.off == len(self.buf):
                        self.buf = bytearray()
                        self.off = 0
                    elif self.off > (1 << 20):
                        del self.buf[: self.off]
                        self.off = 0
                    return msg_type, payload
            self._fill()


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<H", len(b)) + b


def _unpack_str(buf: memoryview, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    return bytes(buf[off : off + n]).decode("utf-8"), off + n


# --- OPEN_MODULE ---------------------------------------------------------

def pack_open_module(params: list[tuple[str, str]], debug: bool) -> bytes:
    out = struct.pack("<BH", int(debug), len(params))
    for k, v in params:
        out += _pack_str(k) + _pack_str(v)
    return out


def unpack_open_module(payload: bytes) -> tuple[list[tuple[str, str]], bool]:
    mv = memoryview(payload)
    debug, n = struct.unpack_from("<BH", mv, 0)
    off = 3
    params = []
    for _ in range(n):
        k, off = _unpack_str(mv, off)
        v, off = _unpack_str(mv, off)
        params.append((k, v))
    return params, bool(debug)


# --- NEW_CONNECTION ------------------------------------------------------

_NEWCONN = struct.Struct("<QQBII")


def pack_new_connection(
    module_id: int,
    conn_id: int,
    ingress: bool,
    src_id: int,
    dst_id: int,
    proto: str,
    src_addr: str,
    dst_addr: str,
    policy_name: str,
    flags: int = 0,
) -> bytes:
    return _NEWCONN.pack(module_id, conn_id, int(ingress), src_id, dst_id) + (
        _pack_str(proto)
        + _pack_str(src_addr)
        + _pack_str(dst_addr)
        + _pack_str(policy_name)
        + bytes([flags & 0xFF])
    )


def unpack_new_connection(payload: bytes):
    mv = memoryview(payload)
    module_id, conn_id, ingress, src_id, dst_id = _NEWCONN.unpack_from(mv, 0)
    off = _NEWCONN.size
    proto, off = _unpack_str(mv, off)
    src_addr, off = _unpack_str(mv, off)
    dst_addr, off = _unpack_str(mv, off)
    policy_name, off = _unpack_str(mv, off)
    # Optional trailing flags byte: a payload from an older shim ends
    # at policy_name — absent means 0 (no retained-mirror claim).
    flags = int(mv[off]) if off < len(mv) else 0
    return (
        module_id,
        conn_id,
        bool(ingress),
        src_id,
        dst_id,
        proto,
        src_addr,
        dst_addr,
        policy_name,
        flags,
    )


class _AnsweredCell:
    """Whether a real (non-suppressed) verdict reply for this batch's
    seq has been emitted — a stall deposal must not shed an item the
    round already served (the client would receive both a
    VERDICT_BATCH and a SHED batch for one seq).  The flag lives in
    the subclass's ``_acell`` one-element list so a batch DERIVED from
    another (a demoted MatrixBatch's DataBatch conversion) can alias
    its origin's state: the real-verdict send marks the copy, the
    deposal/crash sweeps check the original — they must observe one
    flag or the seq is double-replied.  THE one definition, shared by
    both wire batch types; an edit here cannot diverge between them."""

    @property
    def answered(self) -> bool:
        return self._acell[0]

    @answered.setter
    def answered(self, v: bool) -> None:
        self._acell[0] = v


# --- DATA_BATCH ----------------------------------------------------------

@dataclass
class DataBatch(_AnsweredCell):
    seq: int
    conn_ids: np.ndarray  # u64[n]
    flags: np.ndarray  # u8[n]
    lengths: np.ndarray  # u32[n]
    blob: bytes  # concatenated entry payloads
    _offsets: np.ndarray | None = None
    # Containment bookkeeping (service-side, never serialized): absolute
    # monotonic deadline from a DATA_BATCH_DL budget, arrival time for
    # the queue-age watermark, and the _AnsweredCell answered flag.
    deadline: float | None = None
    arrival: float = 0.0
    # Shared-memory transport bookkeeping: seconds between slot commit
    # and doorbell drain (0 for socket-delivered batches) — the
    # tracer's STAGE_RING input.
    ring_wait: float = 0.0
    _acell: list = field(default_factory=lambda: [False])

    @property
    def count(self) -> int:
        return len(self.conn_ids)

    @property
    def offsets(self) -> np.ndarray:
        if self._offsets is None:
            self._offsets = np.concatenate(
                ([0], np.cumsum(self.lengths.astype(np.int64)))
            )
        return self._offsets

    def entry(self, i: int) -> tuple[int, bool, bool, bytes]:
        off = int(self.offsets[i])
        n = int(self.lengths[i])
        f = int(self.flags[i])
        return (
            int(self.conn_ids[i]),
            bool(f & FLAG_REPLY),
            bool(f & FLAG_END_STREAM),
            self.blob[off : off + n],
        )


def pack_data_batch_parts(seq: int, conn_ids, flags, lengths,
                          blob: bytes) -> list[bytes]:
    """The DATA_BATCH frame as scatter-gather parts — THE one
    definition of the layout, shared by the joined socket frame below
    and the shm ring's slot writer (which copies the parts straight
    into the slot, bulk blob last, no intermediate join)."""
    conn_ids = np.ascontiguousarray(conn_ids, "<u8")
    flags = np.ascontiguousarray(flags, "u1")
    lengths = np.ascontiguousarray(lengths, "<u4")
    return [
        struct.pack("<QI", seq, len(conn_ids)),
        conn_ids.tobytes(),
        flags.tobytes(),
        lengths.tobytes(),
        blob,
    ]


def pack_data_batch(
    seq: int,
    conn_ids,
    flags,
    lengths,
    blob: bytes,
) -> bytes:
    return b"".join(pack_data_batch_parts(seq, conn_ids, flags,
                                          lengths, blob))


def unpack_data_batch(payload: bytes) -> DataBatch:
    seq, n = struct.unpack_from("<QI", payload, 0)
    off = 12
    conn_ids = np.frombuffer(payload, "<u8", n, off)
    off += 8 * n
    flags = np.frombuffer(payload, "u1", n, off)
    off += n
    lengths = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    # Ingress stamp, threaded from the wire seam: everything downstream
    # (queue-age shedding, the latency tracer's queue/e2e stages) is
    # anchored at frame decode, not at some later submit point.
    return DataBatch(seq, conn_ids, flags, lengths, payload[off:],
                     arrival=time.monotonic())


def pack_data_batch_dl(
    deadline_us: int, seq: int, conn_ids, flags, lengths, blob: bytes
) -> bytes:
    """DATA_BATCH with a relative deadline budget (µs, capped at u32)."""
    return struct.pack("<I", min(int(deadline_us), 0xFFFFFFFF)) + (
        pack_data_batch(seq, conn_ids, flags, lengths, blob)
    )


def unpack_data_batch_dl(payload: bytes) -> tuple[float, DataBatch]:
    """Returns (deadline budget in seconds, batch)."""
    (deadline_us,) = struct.unpack_from("<I", payload, 0)
    return deadline_us / 1e6, unpack_data_batch(payload[4:])


# --- DATA_MATRIX ---------------------------------------------------------

@dataclass
class MatrixBatch(_AnsweredCell):
    seq: int
    width: int
    conn_ids: np.ndarray  # u64[n]
    lengths: np.ndarray  # u32[n]
    rows: np.ndarray  # u8[n, width], zero-padded past lengths
    flags: int = 0  # MAT_FLAG_* bits
    # Containment bookkeeping (service-side, never serialized):
    # deadline/arrival/ring_wait as in DataBatch, plus _AnsweredCell.
    deadline: float | None = None
    arrival: float = 0.0
    ring_wait: float = 0.0
    _acell: list = field(default_factory=lambda: [False])

    @property
    def count(self) -> int:
        return len(self.conn_ids)


def pack_data_matrix_parts(seq: int, width: int, conn_ids, lengths,
                           rows_bytes: bytes,
                           flags: int = 0) -> list[bytes]:
    """DATA_MATRIX as scatter-gather parts (see
    pack_data_batch_parts: one layout definition for both the socket
    join and the shm slot writer)."""
    conn_ids = np.ascontiguousarray(conn_ids, "<u8")
    lengths = np.ascontiguousarray(lengths, "<u4")
    return [
        struct.pack("<QIIB", seq, len(conn_ids), width, flags),
        conn_ids.tobytes(),
        lengths.tobytes(),
        rows_bytes,
    ]


def pack_data_matrix(seq: int, width: int, conn_ids, lengths,
                     rows_bytes: bytes, flags: int = 0) -> bytes:
    return b"".join(pack_data_matrix_parts(seq, width, conn_ids,
                                           lengths, rows_bytes, flags))


def unpack_data_matrix(payload: bytes) -> MatrixBatch:
    seq, n, width, flags = struct.unpack_from("<QIIB", payload, 0)
    off = 17
    conn_ids = np.frombuffer(payload, "<u8", n, off)
    off += 8 * n
    lengths = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    rows = np.frombuffer(payload, "u1", n * width, off).reshape(n, width)
    # Ingress stamp — see unpack_data_batch.
    return MatrixBatch(seq, width, conn_ids, lengths, rows, flags,
                       arrival=time.monotonic())


# --- VERDICT_BATCH -------------------------------------------------------

@dataclass
class VerdictBatch:
    """One reply to a DATA batch.

    Each entry carries two inject byte ranges, mirroring the two
    per-direction caller-owned inject buffers of the ABI (reference:
    proxylib/libcilium.h OnNewConnection origBuf/replyBuf): ``orig``
    bytes append to the request-direction inject buffer, ``reply`` bytes
    to the reply-direction one (denial responses travel there).  The
    per-entry blob layout is orig-bytes then reply-bytes, entries in
    order.
    """

    seq: int
    conn_ids: np.ndarray  # u64[m] (m >= request count when op lists split)
    results: np.ndarray  # u32[m] FilterResult per entry
    op_counts: np.ndarray  # u32[m], each <= MAX_OPS_PER_ENTRY
    inject_orig_lens: np.ndarray  # u32[m]
    inject_reply_lens: np.ndarray  # u32[m]
    ops: np.ndarray  # FILTER_OP[sum(op_counts)]
    inject_blob: bytes
    _op_offsets: np.ndarray | None = None
    _inj_offsets: np.ndarray | None = None

    @property
    def count(self) -> int:
        return len(self.conn_ids)

    def entry(self, i: int):
        """(conn_id, result, [(op, n_bytes)...], inject_orig, inject_reply)."""
        if self._op_offsets is None:
            self._op_offsets = np.concatenate(
                ([0], np.cumsum(self.op_counts.astype(np.int64)))
            )
            self._inj_offsets = np.concatenate(
                (
                    [0],
                    np.cumsum(
                        self.inject_orig_lens.astype(np.int64)
                        + self.inject_reply_lens.astype(np.int64)
                    ),
                )
            )
        op_off = int(self._op_offsets[i])
        nops = int(self.op_counts[i])
        inj_off = int(self._inj_offsets[i])
        o_n = int(self.inject_orig_lens[i])
        r_n = int(self.inject_reply_lens[i])
        ops = [
            (int(o["op"]), int(o["n_bytes"]))
            for o in self.ops[op_off : op_off + nops]
        ]
        return (
            int(self.conn_ids[i]),
            int(self.results[i]),
            ops,
            self.inject_blob[inj_off : inj_off + o_n],
            self.inject_blob[inj_off + o_n : inj_off + o_n + r_n],
        )


def pack_verdict_body(
    conn_ids,
    results,
    op_counts,
    inject_orig_lens,
    inject_reply_lens,
    ops,
    inject_blob: bytes,
) -> bytes:
    """The columnar verdict arrays without any seq header — shared by
    the single-seq and multi-seq frame layouts."""
    conn_ids = np.ascontiguousarray(conn_ids, "<u8")
    results = np.ascontiguousarray(results, "<u4")
    op_counts = np.ascontiguousarray(op_counts, "<u4")
    inject_orig_lens = np.ascontiguousarray(inject_orig_lens, "<u4")
    inject_reply_lens = np.ascontiguousarray(inject_reply_lens, "<u4")
    ops = np.ascontiguousarray(ops, FILTER_OP)
    return b"".join(
        (
            conn_ids.tobytes(),
            results.tobytes(),
            op_counts.tobytes(),
            inject_orig_lens.tobytes(),
            inject_reply_lens.tobytes(),
            ops.tobytes(),
            inject_blob,
        )
    )


def pack_verdict_batch(
    seq: int,
    conn_ids,
    results,
    op_counts,
    inject_orig_lens,
    inject_reply_lens,
    ops,
    inject_blob: bytes,
) -> bytes:
    return struct.pack("<QI", seq, len(conn_ids)) + pack_verdict_body(
        conn_ids, results, op_counts, inject_orig_lens,
        inject_reply_lens, ops, inject_blob,
    )


def _unpack_verdict_arrays(payload: bytes, off: int, n: int):
    conn_ids = np.frombuffer(payload, "<u8", n, off)
    off += 8 * n
    results = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    op_counts = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    inject_orig_lens = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    inject_reply_lens = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    total_ops = int(op_counts.sum())
    ops = np.frombuffer(payload, FILTER_OP, total_ops, off)
    off += FILTER_OP.itemsize * total_ops
    return (
        conn_ids, results, op_counts, inject_orig_lens,
        inject_reply_lens, ops, off,
    )


def unpack_verdict_batch(payload: bytes) -> VerdictBatch:
    seq, n = struct.unpack_from("<QI", payload, 0)
    (conn_ids, results, op_counts, io_l, ir_l, ops, off) = (
        _unpack_verdict_arrays(payload, 12, n)
    )
    return VerdictBatch(
        seq, conn_ids, results, op_counts, io_l, ir_l, ops, payload[off:]
    )


def pack_verdict_multi(seqs, counts, n: int, body: bytes) -> bytes:
    """One frame answering len(seqs) data batches: per-seq entry counts,
    then one verdict body over all n entries (in seq order)."""
    seqs = np.ascontiguousarray(seqs, "<u8")
    counts = np.ascontiguousarray(counts, "<u4")
    return b"".join(
        (
            struct.pack("<I", len(seqs)),
            seqs.tobytes(),
            counts.tobytes(),
            struct.pack("<I", n),
            body,
        )
    )


def unpack_verdict_multi(payload: bytes) -> list[VerdictBatch]:
    """Split a VERDICT_MULTI frame into per-seq VerdictBatch views
    (numpy slices over the shared payload — no per-entry copies)."""
    (m,) = struct.unpack_from("<I", payload, 0)
    off = 4
    seqs = np.frombuffer(payload, "<u8", m, off)
    off += 8 * m
    counts = np.frombuffer(payload, "<u4", m, off)
    off += 4 * m
    (n,) = struct.unpack_from("<I", payload, off)
    off += 4
    (conn_ids, results, op_counts, io_l, ir_l, ops, off) = (
        _unpack_verdict_arrays(payload, off, n)
    )
    blob = payload[off:]
    ends = np.cumsum(counts.astype(np.int64))
    op_ends = np.concatenate(([0], np.cumsum(op_counts.astype(np.int64))))
    inj_ends = np.concatenate(
        ([0], np.cumsum(io_l.astype(np.int64) + ir_l.astype(np.int64)))
    )
    out = []
    a = 0
    for k in range(m):
        b = int(ends[k])
        opa, opb = int(op_ends[a]), int(op_ends[b])
        ia, ib = int(inj_ends[a]), int(inj_ends[b])
        out.append(
            VerdictBatch(
                int(seqs[k]),
                conn_ids[a:b],
                results[a:b],
                op_counts[a:b],
                io_l[a:b],
                ir_l[a:b],
                ops[opa:opb],
                blob[ia:ib],
            )
        )
        a = b
    return out


# --- SHM doorbell / credit ----------------------------------------------

def pack_shm_doorbell(generation: int, data_tail: int,
                      verdict_head: int) -> bytes:
    """Shim→service: data ring published through ``data_tail``; the
    shim's verdict-ring consume cursor is ``verdict_head`` (credit for
    the service's verdict producer)."""
    return struct.pack("<IQQ", generation, data_tail, verdict_head)


def unpack_shm_doorbell(payload: bytes) -> tuple[int, int, int]:
    return struct.unpack_from("<IQQ", payload, 0)


def pack_shm_credit(generation: int, flags: int, data_head: int,
                    verdict_tail: int) -> bytes:
    """Service→shim: data ring consumed through ``data_head`` (slots
    below it are free), verdict ring published through
    ``verdict_tail``.  ``flags`` carries the quarantine bit (see
    transport.CREDIT_FLAG_QUARANTINED): the session is demoted to the
    socket transport and ring positions >= ``data_head`` were never
    admitted — the shim answers them typed itself (zero silent loss)."""
    return struct.pack("<IIQQ", generation, flags, data_head, verdict_tail)


def unpack_shm_credit(payload: bytes) -> tuple[int, int, int, int]:
    return struct.unpack_from("<IIQQ", payload, 0)


# MSG_SHM_DETACH flag: fire-and-forget (no MSG_ACK reply).  Fault-path
# demotions send this from the shim's reader thread, which cannot wait
# a control round trip — and a stray unsolicited ACK would desync the
# control-reply pairing of the next real RPC.
DETACH_FLAG_NO_ACK = 1


def pack_shm_detach(generation: int, flags: int = 0) -> bytes:
    return struct.pack("<II", generation, flags)


def unpack_shm_detach(payload: bytes) -> tuple[int, int]:
    return struct.unpack_from("<II", payload, 0)


# --- verdict cache (MSG_CACHE_*) -----------------------------------------

# GRANT flag: the claimed verdict is allow (the only claim the cache
# tiers arm on today; a deny claim is never granted — denied frames
# carry per-frame inject side effects the short-circuit would skip).
CACHE_FLAG_ALLOW = 1


def pack_cache_enable() -> bytes:
    """Client opt-in (fire-and-forget, no reply)."""
    return b""


def pack_cache_grant(conn_id: int, epoch: int, rule: int,
                     flags: int = CACHE_FLAG_ALLOW,
                     framing: str = "crlf") -> bytes:
    """Arm one conn: byte-invariant (verdict, rule row) under epoch.

    The trailing framing kind (reasm.FRAMING_*) tells the shim WHICH
    frame-alignment gate guards its local short-circuit — a DNS grant
    must check length-prefix closure, not CRLF tails.  Appended behind
    the original 24-byte form so an old shim keeps working: it reads
    the fixed prefix and ignores the tail, and unpack_cache_grant
    degrades a short (legacy) payload to the CRLF kind, matching the
    only framing grants were ever armed on before (the same
    length-degrading compat move as unpack_ack_epoch)."""
    return struct.pack("<QqiI", conn_id, epoch, rule, flags) + (
        _pack_str(framing)
    )


def unpack_cache_grant(payload: bytes) -> tuple[int, int, int, int, str]:
    conn_id, epoch, rule, flags = struct.unpack_from("<QqiI", payload, 0)
    if len(payload) <= 24:
        return conn_id, epoch, rule, flags, "crlf"
    framing, _ = _unpack_str(memoryview(payload), 24)
    return conn_id, epoch, rule, flags, framing


def pack_cache_revoke(epoch: int) -> bytes:
    """Epoch pointer-flip notification: grants under any OLDER epoch
    are dead (the structural epoch key, client half)."""
    return struct.pack("<q", epoch)


def unpack_cache_revoke(payload: bytes) -> int:
    return struct.unpack_from("<q", payload, 0)[0]


# --- session hello (MSG_SESSION_HELLO) -----------------------------------

def pack_session_hello(identity: str) -> bytes:
    """Shim identity announcement (fire-and-forget, no reply)."""
    import json as _json

    return _json.dumps({"identity": identity}).encode()


def unpack_session_hello(payload: bytes) -> str:
    """Returns the announced identity ('' on a malformed payload — a
    broken hello must never kill the session's read loop; the session
    just keeps its synthetic identity)."""
    import json as _json

    try:
        req = _json.loads(payload.decode()) if payload else {}
        return str(req.get("identity") or "")
    except (ValueError, UnicodeDecodeError, AttributeError):
        return ""


# --- restart handoff (MSG_HANDOFF*) --------------------------------------

# Version of the handoff snapshot schema.  Bumped whenever a field
# changes meaning; restore_handoff refuses a snapshot NEWER than it
# understands (a downgrade must boot cold, never misread warm state)
# and tolerates older ones via per-field defaults.
HANDOFF_VERSION = 1


def pack_handoff(generation: int, deadline_s: float = 5.0) -> bytes:
    """Successor→predecessor: "serialize, fence yourself, step aside".

    ``generation`` is the successor's restart generation — strictly
    greater than the predecessor's, the fencing token late writes are
    rejected against.  ``deadline_s`` bounds how long the predecessor
    may spend quiescing before it must answer."""
    import json as _json

    return _json.dumps(
        {"generation": int(generation), "deadline_s": float(deadline_s)}
    ).encode()


def unpack_handoff(payload: bytes) -> tuple[int, float]:
    """Returns (successor generation, deadline_s); (-1, 0.0) on a
    malformed payload — a broken handoff must not kill the read loop,
    the predecessor just declines."""
    import json as _json

    try:
        req = _json.loads(payload.decode()) if payload else {}
        return int(req["generation"]), float(req.get("deadline_s", 5.0))
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return -1, 0.0


def pack_handoff_reply(snapshot: dict | None, error: str = "") -> bytes:
    """Predecessor→successor: the versioned snapshot, or a typed
    refusal (snapshot None + error set)."""
    import json as _json

    return _json.dumps(
        {"snapshot": snapshot, "error": error}
    ).encode()


def unpack_handoff_reply(payload: bytes) -> tuple[dict | None, str]:
    import json as _json

    try:
        rep = _json.loads(payload.decode()) if payload else {}
        snap = rep.get("snapshot")
        return (snap if isinstance(snap, dict) else None,
                str(rep.get("error") or ""))
    except (ValueError, UnicodeDecodeError, AttributeError):
        return None, "malformed handoff reply"


# --- CLOSE / POLICY_UPDATE / ACK ----------------------------------------

def pack_close(conn_id: int) -> bytes:
    return struct.pack("<Q", conn_id)


def unpack_close(payload: bytes) -> int:
    return struct.unpack_from("<Q", payload, 0)[0]


def pack_policy_update(module_id: int, policies_json: bytes) -> bytes:
    return struct.pack("<QI", module_id, len(policies_json)) + policies_json


def unpack_policy_update(payload: bytes) -> tuple[int, bytes]:
    module_id, n = struct.unpack_from("<QI", payload, 0)
    return module_id, payload[12 : 12 + n]


def pack_ack(status: int) -> bytes:
    return struct.pack("<I", status)


def unpack_ack(payload: bytes) -> int:
    return struct.unpack_from("<I", payload, 0)[0]


def pack_ack_epoch(status: int, epoch: int) -> bytes:
    """MSG_ACK payload for policy updates: status + the committed
    policy-table epoch.  A plain 4-byte ack (pack_ack) remains valid —
    unpack_ack reads the status prefix of either form, and
    unpack_ack_epoch degrades the short form to epoch -1."""
    return struct.pack("<Iq", status, epoch)


def unpack_ack_epoch(payload: bytes) -> tuple[int, int]:
    if len(payload) < 12:
        return unpack_ack(payload), -1
    return struct.unpack_from("<Iq", payload, 0)
