"""Latency decomposition for the verdict hot path.

The north star is ≥1M L7 verdicts/sec/chip at <1ms added p99, but a
number like that is only actionable when the serving path can say WHERE
a verdict's millisecond goes.  This module owns that decomposition:

- **Stage stamps, per round.**  The service stamps each dispatch round
  at its stage boundaries (admit → queue-pop → batch-form →
  device-submit → device-complete → drain → send) and a
  :class:`RoundTrace` turns consecutive stamps into stage durations.
  Everything is recorded per ROUND (one ``Histogram.observe`` per stage
  per round, one e2e observe per wire batch) — never per entry — so the
  always-on cost is O(rounds), not O(verdicts).  The device stage ends
  at a **fenced readback** (``np.asarray``/``device_get`` of the
  output), not ``block_until_ready``: BENCH_NOTES round 4 showed the
  latter returning before execution on the tunneled transport, which
  would book device time as zero and host dispatch as compute.
- **Sampled spans + slow exemplars.**  A lock-light ring buffer keeps
  1-in-N full per-entry spans plus an exemplar for every wire batch
  whose end-to-end latency exceeds ``slow_ms`` — so a specific slow
  request can be NAMED (seq, conn, path, stage breakdown), the way the
  reference pairs always-on counters with a proxy accesslog.  Slow
  exemplars optionally fan out to the monitor stream and to an access
  logger (``LogRecord.latency``).
- **Device telemetry.**  Batch-occupancy and device-busy-fraction
  gauges, fed from the same round stamps.

Timebase: ``time.monotonic()`` throughout, matching the wire batches'
``arrival``/deadline bookkeeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils import metrics

# Serving-path labels (the degradation ladder, fastest first).
PATH_CACHED = "cached"    # established-flow verdict cache (no device)
PATH_VEC = "vec"          # vectorized device path (matrix/vec rounds)
PATH_ORACLE = "oracle"    # entrywise slow path (engines + parsers)
PATH_HOST = "host"        # quarantine host-fallback rounds
PATH_SHED = "shed"        # typed SHED (queue_full / deadline / stall)

# Stage names, in pipeline order.  Each is the duration between two
# consecutive stamp boundaries of a round.
STAGE_RING = "ring"                # shm slot commit -> doorbell drain
STAGE_QUEUE = "queue"              # admit (wire ingress) -> queue pop
STAGE_SWAP = "table_swap"          # round blocked behind an epoch swap
STAGE_REASM = "reasm"              # columnar reassembly (arena ingest +
#                                    frame scan + bucket pack) — carved
#                                    out of batch_form like table_swap
STAGE_CACHE = "cache"              # verdict-cache mask + hit rendering
#                                    (established-flow short-circuit) —
#                                    carved out of batch_form the same
#                                    way; a cached round's only real
#                                    work shows up here
STAGE_FORM = "batch_form"          # pop -> device batch assembled
STAGE_SUBMIT = "device_submit"     # assembled -> device calls issued
STAGE_DEVICE = "device"            # issued -> fenced readback complete
STAGE_DRAIN = "drain"              # complete -> responses built
STAGE_SEND = "send"                # built -> verdict frames written

STAGES = (STAGE_RING, STAGE_QUEUE, STAGE_SWAP, STAGE_REASM, STAGE_CACHE,
          STAGE_FORM, STAGE_SUBMIT, STAGE_DEVICE, STAGE_DRAIN, STAGE_SEND)


class RoundTrace:
    """Stamp carrier for one dispatch round (one path group).

    Created at queue-pop, stamped at each boundary, finished once the
    round's verdict frames are on the wire.  Stamps are idempotent
    (first writer wins) so paths that skip a boundary inherit the
    previous one and the stage reads as zero instead of negative.
    """

    __slots__ = ("path", "n", "t_admit", "t_pop", "t_form", "t_submit",
                 "t_complete", "t_drain", "t_send", "ring_s", "swap_s",
                 "reasm_s", "cache_s", "formation")

    def __init__(self, path: str, n: int, t_admit: float, t_pop: float,
                 ring_s: float = 0.0, swap_s: float = 0.0):
        self.path = path
        self.n = n
        # t_admit is the OLDEST covered wire batch's ingress stamp, so
        # the queue stage reports the round's worst queue wait.
        self.t_admit = t_admit or t_pop
        self.t_pop = t_pop
        self.t_form = 0.0
        self.t_submit = 0.0
        self.t_complete = 0.0
        self.t_drain = 0.0
        self.t_send = 0.0
        # Shared-memory transport: worst slot-commit → doorbell-drain
        # wait across the round's batches.  Carved OUT of the queue
        # stage (arrival is the slot-commit stamp for ring batches) so
        # the decomposition shows what the copy elimination bought.
        self.ring_s = ring_s
        # Time this round spent blocked behind a policy-epoch table
        # swap (the pointer flip holds the round-snapshot lock).
        # Carved OUT of batch_form so a swap stall is visible as its
        # own stage instead of reading as batch-assembly cost.
        self.swap_s = swap_s
        # Columnar-reassembly work (arena ingest + frame scan + bucket
        # pack, sidecar/reasm.py) — carved out of batch_form the same
        # way, so the mixed-path decomposition names the reassembler's
        # cost instead of folding it into batch assembly.
        self.reasm_s = 0.0
        # Verdict-cache work (vectorized hit mask + cached-frame
        # rendering) — carved out of batch_form like reasm; for a
        # fully-cached round this IS the round's host cost.
        self.cache_s = 0.0
        # Batch-formation provenance (sidecar/ledger.py): the
        # dispatcher's per-round pop stamp — trigger, queue depth,
        # oldest-entry age and bytes at issue — captured at
        # begin_round from the popping thread.  None when the round
        # was begun off the dispatch path (no stamp, no guess).
        self.formation = None

    def formed(self) -> None:
        if not self.t_form:
            self.t_form = time.monotonic()

    def submitted(self) -> None:
        if not self.t_submit:
            self.t_submit = time.monotonic()

    def completed(self) -> None:
        if not self.t_complete:
            self.t_complete = time.monotonic()

    def drained(self) -> None:
        if not self.t_drain:
            self.t_drain = time.monotonic()

    def stages(self) -> dict[str, float]:
        """Stage durations in seconds (>= 0; skipped boundaries fall
        back to the previous stamp, reading as a zero-length stage)."""
        t_pop = self.t_pop
        t_form = self.t_form or t_pop
        t_submit = self.t_submit or t_form
        t_complete = self.t_complete or t_submit
        t_drain = self.t_drain or t_complete
        t_send = self.t_send or t_drain
        wait = max(t_pop - self.t_admit, 0.0)
        ring = min(max(self.ring_s, 0.0), wait)
        form = max(t_form - t_pop, 0.0)
        swap = min(max(self.swap_s, 0.0), form)
        reasm = min(max(self.reasm_s, 0.0), form - swap)
        cache = min(max(self.cache_s, 0.0), form - swap - reasm)
        return {
            STAGE_RING: ring,
            STAGE_QUEUE: wait - ring,
            STAGE_SWAP: swap,
            STAGE_REASM: reasm,
            STAGE_CACHE: cache,
            STAGE_FORM: form - swap - reasm - cache,
            STAGE_SUBMIT: max(t_submit - t_form, 0.0),
            STAGE_DEVICE: max(t_complete - t_submit, 0.0),
            STAGE_DRAIN: max(t_drain - t_complete, 0.0),
            STAGE_SEND: max(t_send - t_drain, 0.0),
        }


class VerdictTracer:
    """Per-service latency tracer: stage histograms, a bounded span
    ring, slow exemplars, occupancy/busy gauges.

    Lock-light by design: the ring is a ``deque(maxlen=...)`` (GIL-
    atomic appends), the per-stage accumulators take ONE short lock per
    round, and the sampled-span decision is a counter compare.  Nothing
    here is per-entry.
    """

    # Device-busy gauge window (seconds of wall clock per update).
    BUSY_WINDOW_S = 1.0

    def __init__(self, *, sample_every: int = 4096, slow_ms: float = 50.0,
                 ring: int = 512, stage_metrics: bool = True,
                 batch_capacity: int = 1):
        self.sample_every = max(int(sample_every), 0)
        self.slow_s = slow_ms / 1e3
        self.stage_metrics = stage_metrics
        self.batch_capacity = max(int(batch_capacity), 1)
        self._ring: deque = deque(maxlen=max(int(ring), 1))
        self._lock = threading.Lock()
        # (stage, path) -> [rounds, total_seconds] — the status()
        # aggregate (the registry histograms are process-global; these
        # are THIS service's numbers).
        self._acc: dict[tuple[str, str], list] = {}
        self.rounds = 0
        self.entries = 0
        self.spans_sampled = 0
        self.slow_exemplars = 0
        self.shed_spans = 0
        self._sample_credit = 0
        # Device-busy window accounting.
        self._win_start = time.monotonic()
        self._win_device_s = 0.0
        # Optional fan-out for slow exemplars.
        self.monitor = None          # monitor.Monitor (notify())
        self.access_logger = None    # accesslog.logger.AccessLogger (log())
        # Optional flight recorder (blackbox.FlightRecorder): fed the
        # same per-round numbers the busy gauge uses, so the occupancy
        # time-series costs no extra stamps.
        self.recorder = None
        # Optional device ledger (ledger.DeviceLedger): fed the
        # formation stamp the dispatcher left on the popping thread —
        # one stamp_round per round, riding this same close.
        self.ledger = None

    # -- round lifecycle --------------------------------------------------

    def begin_round(self, path: str, n: int, t_admit: float,
                    t_pop: float | None = None,
                    ring_s: float = 0.0,
                    swap_s: float = 0.0) -> RoundTrace:
        rt = RoundTrace(path, n, t_admit, t_pop or time.monotonic(),
                        ring_s, swap_s)
        # The dispatcher stamps formation provenance on the thread that
        # popped (or inlined) the round; begin_round runs on that same
        # thread, so the capture is a plain attribute read.
        rt.formation = getattr(
            threading.current_thread(), "_disp_pop", None
        )
        return rt

    def finish_round(self, rt: RoundTrace, batches=()) -> None:
        """Close a round: observe each stage once, the e2e histogram
        once per covered wire batch, refresh the gauges, and capture
        sampled/slow spans.  ``batches`` is an iterable of
        ``(seq, n, arrival, conn0)`` describing the wire batches the
        round answered."""
        now = time.monotonic()
        if not rt.t_send:
            rt.t_send = now
        stages = rt.stages()
        path = rt.path
        if self.stage_metrics:
            h = metrics.VerdictStageSeconds
            if stages[STAGE_RING]:
                # Socket rounds have no ring stage; observing a
                # permanent zero would just pad the histogram.
                h.observe(stages[STAGE_RING], STAGE_RING, path)
            if stages[STAGE_SWAP]:
                # Only rounds that actually blocked behind an epoch
                # swap carry the stage (same rationale as ring).
                h.observe(stages[STAGE_SWAP], STAGE_SWAP, path)
            if stages[STAGE_REASM]:
                # Only columnar-reassembly rounds carry the stage
                # (same rationale as ring/table_swap).
                h.observe(stages[STAGE_REASM], STAGE_REASM, path)
            h.observe(stages[STAGE_QUEUE], STAGE_QUEUE, path)
            h.observe(stages[STAGE_FORM], STAGE_FORM, path)
            h.observe(stages[STAGE_SUBMIT], STAGE_SUBMIT, path)
            h.observe(stages[STAGE_DEVICE], STAGE_DEVICE, path)
            h.observe(stages[STAGE_DRAIN], STAGE_DRAIN, path)
            h.observe(stages[STAGE_SEND], STAGE_SEND, path)
            metrics.VerdictBatchOccupancy.set(
                min(rt.n / self.batch_capacity, 1.0)
            )
        with self._lock:
            self.rounds += 1
            self.entries += rt.n
            for stage in STAGES:
                rec = self._acc.get((stage, path))
                if rec is None:
                    rec = self._acc[(stage, path)] = [0, 0.0]
                rec[0] += 1
                rec[1] += stages[stage]
            # Device-busy fraction, windowed.
            self._win_device_s += stages[STAGE_DEVICE]
            span = now - self._win_start
            if span >= self.BUSY_WINDOW_S:
                if self.stage_metrics:
                    metrics.DeviceBusyFraction.set(
                        min(self._win_device_s / span, 1.0)
                    )
                self._win_start = now
                self._win_device_s = 0.0
            sample = False
            if self.sample_every:
                self._sample_credit += rt.n
                if self._sample_credit >= self.sample_every:
                    self._sample_credit %= self.sample_every
                    sample = True
        for desc in batches:
            # Descs are (seq, n, arrival, conn0[, session]) — the
            # session id rides along where the fan-in seam knows it, so
            # an exemplar can be attributed to one shim (pod).
            seq, n, arrival, conn0 = desc[0], desc[1], desc[2], desc[3]
            session = desc[4] if len(desc) > 4 else 0
            e2e = max(rt.t_send - (arrival or rt.t_admit), 0.0)
            if self.stage_metrics:
                metrics.VerdictE2ESeconds.observe(e2e, path)
            slow = e2e >= self.slow_s
            if sample or slow:
                self._span(
                    "slow" if slow else "sample", path, seq, n, conn0,
                    e2e, stages, session=session,
                )
                sample = False  # one sampled span per round
        rec = self.recorder
        if rec is not None:
            try:
                rec.sample_round(rt.n, self.batch_capacity,
                                 stages[STAGE_DEVICE], now)
            except Exception:  # noqa: BLE001 — recorder must not cost the round
                pass
        led = self.ledger
        form = rt.formation
        if led is not None and form is not None:
            try:
                led.stamp_round(
                    form.get("trigger", "idle-greedy"), rt.n,
                    self.batch_capacity,
                    depth=form.get("depth", 0),
                    age_s=form.get("age_s", 0.0),
                    bytes_at_issue=form.get("bytes", 0),
                )
            except Exception:  # noqa: BLE001 — ledger must not cost the round
                pass

    def record_shed(self, seq: int, n: int, arrival: float, conn0: int,
                    reason: str, session: int = 0) -> None:
        """A typed SHED answered this wire batch: record its e2e under
        the shed path (its only real stage is queue wait) and keep an
        exemplar — shed entries are the tail the decomposition exists
        to explain."""
        now = time.monotonic()
        e2e = max(now - arrival, 0.0) if arrival else 0.0
        if self.stage_metrics:
            metrics.VerdictE2ESeconds.observe(e2e, PATH_SHED)
            metrics.VerdictStageSeconds.observe(e2e, STAGE_QUEUE, PATH_SHED)
        with self._lock:
            self.shed_spans += 1
            rec = self._acc.get((STAGE_QUEUE, PATH_SHED))
            if rec is None:
                rec = self._acc[(STAGE_QUEUE, PATH_SHED)] = [0, 0.0]
            rec[0] += 1
            rec[1] += e2e
        self._span("shed", PATH_SHED, seq, n, conn0, e2e,
                   {STAGE_QUEUE: e2e}, reason=reason, session=session)

    # -- spans / exemplars ------------------------------------------------

    def _span(self, kind: str, path: str, seq: int, n: int, conn0: int,
              e2e: float, stages: dict, reason: str = "",
              session: int = 0) -> None:
        span = {
            "kind": kind,
            "path": path,
            "seq": int(seq),
            "entries": int(n),
            "conn_id": int(conn0),
            "e2e_us": round(e2e * 1e6, 1),
            "stages_us": {
                k: round(v * 1e6, 1) for k, v in stages.items()
            },
            "ts": time.time(),
        }
        if session:
            span["session"] = int(session)
        if reason:
            span["reason"] = reason
        self._ring.append(span)
        metrics.VerdictTraceSpans.inc(kind)
        if kind == "sample":
            with self._lock:
                self.spans_sampled += 1
            return
        if kind == "slow":
            # Shed spans are counted in record_shed (shed_spans) only:
            # booking them here too would read as a latency-threshold
            # breach that never happened under pure overload.
            with self._lock:
                self.slow_exemplars += 1
        self._emit_slow(span)

    def _emit_slow(self, span: dict) -> None:
        """Fan a slow/shed exemplar out to the monitor stream and the
        access log (both optional, both contained — an exemplar sink
        failure never touches the serving path)."""
        mon = self.monitor
        if mon is not None:
            try:
                from ..monitor.monitor import MSG_TYPE_TRACE, MonitorEvent

                mon.notify(
                    MonitorEvent(MSG_TYPE_TRACE, {"slow_verdict": span})
                )
            except Exception:  # noqa: BLE001 — sink must not poison path
                pass
        logger = self.access_logger
        if logger is not None:
            try:
                logger.log(accesslog_record_for_span(span))
            except Exception:  # noqa: BLE001
                pass

    def spans(self, n: int = 100, kind: str | None = None,
              session: int | None = None) -> list[dict]:
        """Most-recent-first snapshot of the span ring.  ``session``
        filters to spans attributed to one fan-in session (`cilium
        sidecar trace --session`)."""
        out = [s for s in reversed(list(self._ring))
               if (kind is None or s["kind"] == kind)
               and (session is None or s.get("session") == session)]
        return out[: max(int(n), 0)]

    # -- status -----------------------------------------------------------

    def status(self) -> dict:
        """Per-stage means (µs) by path for `cilium sidecar status`,
        plus the span/exemplar counters.  p99 column comes from the
        process-global stage histogram (bucket upper bound)."""
        with self._lock:
            acc = {k: list(v) for k, v in self._acc.items()}
            out = {
                "rounds": self.rounds,
                "entries": self.entries,
                "spans_sampled": self.spans_sampled,
                "slow_exemplars": self.slow_exemplars,
                "shed_spans": self.shed_spans,
                "sample_every": self.sample_every,
                "slow_threshold_ms": round(self.slow_s * 1e3, 3),
            }
        stages: dict[str, dict] = {}
        for (stage, path), (count, total) in sorted(acc.items()):
            p99 = metrics.VerdictStageSeconds.quantile(0.99, stage, path)
            stages.setdefault(path, {})[stage] = {
                "rounds": count,
                "mean_us": round(total / count * 1e6, 1) if count else 0.0,
                "p99_us": round(p99 * 1e6, 1) if p99 is not None else None,
            }
        out["stages"] = stages
        return out


def format_stages_us(stages_us: dict) -> str:
    """Render a span's stage breakdown for humans, largest stage first,
    sub-µs noise dropped — THE one definition shared by the monitor
    stream's SLOW-VERDICT line and `cilium sidecar trace` (they must
    never drift: an operator correlates one against the other)."""
    return " ".join(
        f"{k}={v:.0f}us"
        for k, v in sorted(stages_us.items(), key=lambda kv: -kv[1])
        if v >= 1.0
    )


def accesslog_record_for_span(span: dict):
    """Annotate a slow-verdict exemplar onto a canonical access-log
    record (the accesslog analog of the monitor event): a Sample-type
    LogRecord whose ``latency`` field carries the stage breakdown."""
    from ..accesslog.record import (
        FLOW_TYPE_SAMPLE,
        LatencyInfo,
        LogRecord,
        L7LogEntry,
    )

    return LogRecord(
        type=FLOW_TYPE_SAMPLE,
        info=(
            f"slow verdict: path={span['path']} seq={span['seq']} "
            f"conn={span['conn_id']} e2e={span['e2e_us']:.0f}us"
        ),
        l7=L7LogEntry(proto="verdict-trace", fields={
            "kind": span["kind"],
            **({"reason": span["reason"]} if span.get("reason") else {}),
        }),
        latency=LatencyInfo(
            total_us=span["e2e_us"],
            path=span["path"],
            stages_us=dict(span["stages_us"]),
        ),
    )
