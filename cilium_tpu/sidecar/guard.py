"""Device quarantine state machine for the verdict hot path.

Per-packet-ML dataplanes treat bounded-latency degradation — not
availability loss — as the contract when the accelerator path stalls
(Taurus, arXiv:2002.08987; the kernel L7-offload line makes the same
call).  This module owns the state machine that enforces it for the
sidecar:

- a device call that exceeds the watchdog deadline (TPU stall, compile
  storm) **quarantines** the device: subsequent rounds bypass the
  device entirely and render verdicts through the bit-identical host
  fallback (the proxylib oracle / the device-assisted engines' host
  ``policy.matches`` path);
- repeated crashed rounds (a poisoned engine) quarantine the same way
  via ``record_failure``;
- while quarantined, traffic-driven **re-probes** run a real device
  call on a disposable executor under the same deadline; the first
  probe that completes heals the quarantine, so recovery is automatic
  and requires no operator action.

A stuck probe/worker thread cannot be cancelled in Python — it is
abandoned (daemon, bounded by one per probe interval) and its executor
discarded; the number of leaked threads is bounded by the number of
distinct stalls, not by traffic.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..analysis.protocols import (
    DEVICE_GUARD_PROTOCOL,
    DEVICE_LOST,
    DEVICE_OK,
    GUARD_QUARANTINED,
    GUARD_SERVING,
    MESH_DEVICE_PROTOCOL,
)
from . import blackbox

log = logging.getLogger(__name__)


class DeviceStall(Exception):
    """A device call exceeded the watchdog deadline."""


class DeviceGuard:
    """Quarantine latch + automatic re-probe.

    ``timeout_s`` bounds one device round (and one probe);
    ``reprobe_interval_s`` paces traffic-driven probes while
    quarantined; ``fail_threshold`` consecutive crashed rounds trip the
    quarantine without a stall (0 disables that trigger).
    ``on_change(quarantined: bool)`` fires on every transition (metrics
    / monitor hookup).
    """

    def __init__(
        self,
        timeout_s: float = 10.0,
        reprobe_interval_s: float = 1.0,
        fail_threshold: int = 3,
        on_change=None,
    ):
        self.timeout_s = timeout_s
        self.reprobe_interval_s = reprobe_interval_s
        self.fail_threshold = fail_threshold
        self.on_change = on_change
        self._lock = threading.Lock()
        # The quarantine latch is a DECLARED typestate (protocols.py):
        # every flip routes through DEVICE_GUARD_PROTOCOL.advance, and
        # the public ``quarantined`` bool is a read-only view.
        self._latch = GUARD_SERVING
        self.reason = ""
        self.stalls = 0
        self.quarantine_events = 0
        self.probes = 0
        self._crash_streak = 0
        # Set by record_failure, consumed by record_ok, cleared at
        # round_start (round-local): a round that CONTAINED a failure
        # (typed errors, host fallback) still completes, and its
        # record_ok must not reset the streak — only a genuinely clean
        # round does.
        self._tainted = False
        # Sticky variant for DEFERRED failures (a round's async
        # completion crashing on the send-loop thread, possibly in the
        # gap between dispatcher rounds): round_start must NOT erase it
        # — otherwise the next round's clean record_ok resets the
        # streak and an engine whose every deferred completion crashes
        # never reaches fail_threshold.  Consumed (without a reset) by
        # the next record_ok, like the original taint.
        self._sticky_taint = False
        # deferred_scope marks the calling thread so record_failure
        # picks sticky semantics without plumbing flags through every
        # engine hook / pump call site.
        self._tls = threading.local()
        self._probe_inflight = False
        self._last_probe = 0.0
        self._quarantined_at = 0.0
        # Per-device health table (the mesh width ladder's fault
        # attribution surface): one row per mesh device that ever
        # raised, stalled, or vanished from the backend's device set —
        # keyed by the stringified device id (JSON-safe for the
        # restart handoff).  "lost" rows are the devices the off-path
        # reshape holds out of the serving mesh; fault counters are
        # lifetime (a healed device keeps its history so a flapping
        # chip is visible to the operator).
        self._devices: dict[str, dict] = {}
        # Cumulative seconds spent quarantined (closed intervals; the
        # live interval is added in status()) — the "how long were we
        # on the host fallback" device-telemetry number.
        self._quarantined_total_s = 0.0

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    @property
    def quarantined(self) -> bool:
        return self._latch == GUARD_QUARANTINED

    # -- transitions ------------------------------------------------------

    def quarantine(self, reason: str) -> None:
        with self._lock:
            if self._latch == GUARD_QUARANTINED:
                return
            with blackbox.annotate(reason=reason):
                self._latch = DEVICE_GUARD_PROTOCOL.advance(
                    self._latch, GUARD_QUARANTINED
                )
            self.reason = reason
            self.quarantine_events += 1
            self._quarantined_at = time.monotonic()
            # The next probe may fire immediately.
            self._last_probe = 0.0
        log.warning("device quarantined: %s", reason)
        if self.on_change is not None:
            try:
                self.on_change(True)
            except Exception:  # noqa: BLE001 — hook must not poison state
                log.exception("quarantine on_change hook failed")

    def record_stall(self, reason: str = "device-stall") -> None:
        with self._lock:
            self.stalls += 1
        self.quarantine(reason)

    def record_failure(self, reason: str = "model-error",
                       sticky: bool = False) -> None:
        """One crashed/contained-failed dispatch round; quarantine on a
        streak of them.  ``sticky`` (or a surrounding deferred_scope)
        marks a deferred-completion failure whose taint must survive
        the next round_start."""
        with self._lock:
            self._crash_streak += 1
            if sticky or getattr(self._tls, "sticky", False):
                self._sticky_taint = True
            else:
                self._tainted = True
            trip = (
                self.fail_threshold
                and self._crash_streak >= self.fail_threshold
            )
        if trip:
            self.quarantine(f"{reason} x{self._crash_streak}")

    def deferred_scope(self, fn, *args, **kwargs):
        """Run ``fn`` with record_failure in STICKY mode: the send loop
        uses this around deferred round completions (entry2 finishes),
        whose pump/judge crashes land outside any dispatcher round and
        would otherwise be erased by the next round_start."""
        self._tls.sticky = True
        try:
            return fn(*args, **kwargs)
        finally:
            self._tls.sticky = False

    def round_start(self) -> None:
        """A new dispatch round begins: the ROUND-LOCAL taint is
        cleared.  A round that CRASHES never reaches record_ok, so its
        taint would otherwise survive and swallow the NEXT clean
        round's record_ok without resetting the streak — alternating
        crash/clean rounds would still accumulate to fail_threshold,
        contradicting the 'consecutive crashed rounds' semantics.  The
        sticky (deferred-failure) taint is deliberately NOT cleared
        here — it belongs to no dispatcher round and is consumed by
        the next record_ok instead."""
        with self._lock:
            self._tainted = False

    def record_ok(self) -> None:
        """End of a completed round: resets the streak ONLY if the
        round recorded no contained failure (a pump/judge crash that
        was answered with typed errors still counts toward the
        poisoned-engine streak)."""
        with self._lock:
            if self._tainted or self._sticky_taint:
                self._tainted = False
                self._sticky_taint = False
                return
            self._crash_streak = 0

    def _heal(self) -> None:
        with self._lock:
            if self._latch != GUARD_QUARANTINED:
                return
            with blackbox.annotate(reason="probe-heal"):
                self._latch = DEVICE_GUARD_PROTOCOL.advance(
                    self._latch, GUARD_SERVING
                )
            self.reason = ""
            self._crash_streak = 0
            self._tainted = False
            self._sticky_taint = False
            self._quarantined_total_s += (
                time.monotonic() - self._quarantined_at
            )
        log.warning("device un-quarantined (probe succeeded)")
        if self.on_change is not None:
            try:
                self.on_change(False)
            except Exception:  # noqa: BLE001
                log.exception("quarantine on_change hook failed")

    # -- per-device health (mesh width ladder) ----------------------------

    def record_device_fault(self, device, reason: str) -> None:
        """Attribute one mesh fault (readback error, stall, vanish) to
        a SPECIFIC device: the row flips to "lost" and the typed fault
        counter bumps.  The reshape/re-promotion ladder reads the lost
        set; the operator reads the lifetime counters."""
        key = str(device)
        with self._lock:
            row = self._devices.setdefault(
                key, {"state": DEVICE_OK, "faults": {}, "heals": 0}
            )
            with blackbox.annotate(reason=reason, device=key):
                row["state"] = MESH_DEVICE_PROTOCOL.advance(
                    row["state"], DEVICE_LOST
                )
            row["faults"][reason] = row["faults"].get(reason, 0) + 1
        log.warning("mesh device %s marked lost: %s", key, reason)

    def mark_device_ok(self, device) -> None:
        """A previously-lost device answered its probe: the row heals
        (state "ok", heal counter bumps) — fault history is kept."""
        key = str(device)
        with self._lock:
            row = self._devices.get(key)
            if row is None or row["state"] == DEVICE_OK:
                return
            with blackbox.annotate(reason="probe-heal", device=key):
                row["state"] = MESH_DEVICE_PROTOCOL.advance(
                    row["state"], DEVICE_OK
                )
            row["heals"] = row.get("heals", 0) + 1
        log.warning("mesh device %s healed (probe succeeded)", key)

    def lost_devices(self) -> list[str]:
        with self._lock:
            return sorted(
                k for k, r in self._devices.items()
                if r["state"] == DEVICE_LOST
            )

    def device_table(self) -> dict:
        """JSON-safe copy of the health table (status surface + the
        restart handoff snapshot)."""
        with self._lock:
            return {
                k: {
                    "state": r["state"],
                    "faults": dict(r["faults"]),
                    "heals": int(r.get("heals", 0)),
                }
                for k, r in sorted(self._devices.items())
            }

    # -- re-probe ---------------------------------------------------------

    def maybe_reprobe(self, probe_fn) -> None:
        """Traffic-driven: called once per dispatch round.  At most one
        probe in flight; paced by ``reprobe_interval_s``.  The probe
        runs ``probe_fn`` on a fresh single-thread executor bounded by
        ``timeout_s`` — a probe that hangs is abandoned with its
        executor and quarantine holds."""
        if not self.quarantined:
            return
        now = time.monotonic()
        with self._lock:
            if self._probe_inflight:
                return
            if now - self._last_probe < self.reprobe_interval_s:
                return
            self._probe_inflight = True
            self._last_probe = now
            self.probes += 1

        def run() -> None:
            ex = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="device-probe"
            )
            try:
                fut = ex.submit(probe_fn)
                fut.result(self.timeout_s or 5.0)
            except Exception:  # noqa: BLE001 — timeout or device error
                log.debug("device re-probe failed; quarantine holds")
            else:
                self._heal()
            finally:
                ex.shutdown(wait=False)
                with self._lock:
                    self._probe_inflight = False

        threading.Thread(
            target=run, daemon=True, name="device-reprobe"
        ).start()

    # -- restart handoff --------------------------------------------------

    def snapshot_state(self) -> dict:
        """Serialize the latch + lifetime counters for the service's
        restart handoff snapshot: a successor must inherit an open
        quarantine (the device did not heal just because the proxy
        restarted), and the operator's event counters must not reset
        to zero mid-incident.  Every field here is consumed by
        ``restore_state`` (lint R17 audits the pair)."""
        with self._lock:
            return {
                "quarantined": self.quarantined,
                "reason": self.reason,
                "stalls": self.stalls,
                "quarantine_events": self.quarantine_events,
                "probes": self.probes,
                "quarantined_total_s": self._quarantined_total_s,
                "devices": {
                    k: {
                        "state": r["state"],
                        "faults": dict(r["faults"]),
                        "heals": int(r.get("heals", 0)),
                    }
                    for k, r in self._devices.items()
                },
            }

    def restore_state(self, snap: dict) -> None:
        """Successor half: adopt the predecessor's latch.  Malformed or
        empty input restores nothing (cold guard state is fail-open
        toward the device, which is correct — the first stall re-trips
        the latch).  Restoring an OPEN quarantine re-arms the probe
        pacer so traffic heals it exactly as it would have in the
        predecessor — including a restart racing the heal probe: the
        in-flight probe died with the old process, the successor just
        probes again."""
        try:
            quarantined = snap["quarantined"]
            if not isinstance(quarantined, bool):
                # A JSON snapshot writes a real bool; anything else is
                # corruption — refuse the row whole (bool("garbage")
                # would silently restore an OPEN quarantine).
                return
            reason = str(snap.get("reason", ""))
            stalls = int(snap.get("stalls", 0))
            events = int(snap.get("quarantine_events", 0))
            probes = int(snap.get("probes", 0))
            total_s = float(snap.get("quarantined_total_s", 0.0))
        except (KeyError, TypeError, ValueError):
            return
        # Versioned-in per-device health table (.get: absent in
        # pre-PR-17 snapshots).  Rows are type-checked individually —
        # a malformed row is dropped, never half-restored (a wrongly
        # "lost" device would keep a healthy chip out of the mesh).
        devices: dict = {}
        for k, r in (snap.get("devices") or {}).items():
            if not isinstance(r, dict):
                continue
            state = r.get("state")
            if state not in (DEVICE_OK, DEVICE_LOST):
                continue
            try:
                faults = {
                    str(fk): int(fv)
                    for fk, fv in (r.get("faults") or {}).items()
                }
                heals = int(r.get("heals", 0))
            except (TypeError, ValueError):
                continue
            devices[str(k)] = {
                "state": state, "faults": faults, "heals": heals,
            }
        with self._lock:
            self.stalls = stalls
            self.quarantine_events = events
            self.probes = probes
            self._quarantined_total_s = total_s
            if devices:
                self._devices = devices
            if quarantined and self._latch != GUARD_QUARANTINED:
                with blackbox.annotate(reason=reason or "restored"):
                    self._latch = DEVICE_GUARD_PROTOCOL.advance(
                        self._latch, GUARD_QUARANTINED
                    )
                self.reason = reason or "restored"
                self._quarantined_at = time.monotonic()
                self._last_probe = 0.0  # probe may fire immediately

    # -- observability ----------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            total = self._quarantined_total_s
            if self.quarantined:
                total += time.monotonic() - self._quarantined_at
            out = {
                "quarantined": self.quarantined,
                "reason": self.reason,
                "stalls": self.stalls,
                "quarantine_events": self.quarantine_events,
                "probes": self.probes,
                "quarantined_total_s": round(total, 3),
            }
            if self.quarantined:
                out["quarantined_for_s"] = round(
                    time.monotonic() - self._quarantined_at, 3
                )
            if self._devices:
                out["devices"] = {
                    k: {
                        "state": r["state"],
                        "faults": dict(r["faults"]),
                        "heals": int(r.get("heals", 0)),
                    }
                    for k, r in sorted(self._devices.items())
                }
            return out
