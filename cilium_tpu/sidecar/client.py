"""Datapath-side shim: per-connection buffering + the OnIO contract.

The Python twin of the native C++ shim (``native/shim.cc``): connects to
the verdict service, registers connections, ships byte batches, and
applies returned FilterOps to its buffers with the exact byte-accounting
semantics of the reference's Envoy-side consumer
(reference: envoy/cilium_proxylib.cc:125-214 GoFilter::Instance::OnIO —
pre-pass/pre-drop counters, need_bytes gating, reverse-direction inject
output, INJECT from the per-direction inject slice, ≤16 ops applied per
round with continuation).

Used by tests (op/byte parity against the in-process oracle) and by the
latency bench (batched async mode).

Fault containment (the fail-closed contract):

- A dead socket surfaces a typed ``SidecarUnavailable`` immediately —
  never a raw OSError, never a hang until the RPC timeout.
- ``ShimConnection.on_io`` NEVER raises and NEVER hangs on service
  loss: it drops the direction's retained bytes (fail-closed — nothing
  passes unverdicted) and returns ``SERVICE_UNAVAILABLE``.
- With ``auto_reconnect=True`` the client redials with jittered
  exponential backoff and REPLAYS its session (modules, policies,
  registered connections) so verdicts resume without caller
  involvement.  Retry classification follows the kvstore client
  (utils/backoff, PR 1): control RPCs (open_module, policy_update,
  new_connection, status) are idempotent at the service and retried
  once after a reconnect; data RPCs are NEVER retried — their bytes
  were dropped fail-closed, and a replay could double-apply ops.
- ``deadline_ms`` stamps every data RPC with a wire deadline budget
  (MSG_DATA_BATCH_DL) so the service sheds — typed — rather than serve
  a verdict the datapath has already given up on.
"""

from __future__ import annotations

import itertools
import json
import logging
import socket
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field

import numpy as np

from ..analysis.protocols import (
    GRANT_ARMED,
    GRANT_NONE,
    GRANT_PROTOCOL,
)
from ..proxylib.types import DROP, ERROR, INJECT, MORE, PASS, FilterResult
from ..utils import metrics
from ..utils.backoff import Exponential
from ..utils.sockutil import shutdown_close as _teardown
from . import wire
from .reasm import FRAMING_CRLF, FRAMINGS, rows_end_crlf, segments_end_crlf
from .shm import RingError
from .transport import (
    CREDIT_FLAG_QUARANTINED,
    REASON_ATTACH_REJECTED,
    REASON_OVERSIZE,
    REASON_OVERSIZE_SPREE,
    REASON_RING_FULL,
    REASON_TORN_SLOT,
    TRANSPORT_SHM,
    TRANSPORT_SOCKET,
    ShmSession,
)

log = logging.getLogger(__name__)

# Per-framing shim grants (ROADMAP 3c): the wire carries each grant's
# framing KIND string; the hot path indexes these compact code tables.
# Sorted so both ends derive the same coding independently of insertion
# order; -1 in the per-conn code array means "no grant".
_FRAMING_KINDS = sorted(FRAMINGS)
_FRAMING_CODES = {k: i for i, k in enumerate(_FRAMING_KINDS)}
_FRAMING_BY_CODE = [FRAMINGS[k] for k in _FRAMING_KINDS]
_CODE_CRLF = _FRAMING_CODES[FRAMING_CRLF]


def _join(payload) -> bytes:
    """Materialize a scatter-gather payload for the socket path (the
    ring path writes the parts into the slot without this copy)."""
    if isinstance(payload, (list, tuple)):
        return b"".join(payload)
    return payload


class SidecarUnavailable(wire.WireError):
    """The verdict service is unreachable (typed, raised immediately —
    callers decide between fail-closed verdicts and retry-after-
    reconnect; see the module docstring's classification)."""


class SidecarRestarting(SidecarUnavailable):
    """The service is down but this client's restart survival window
    is open (``restart_grace_s``): granted flows keep serving locally,
    and non-granted work is queued bounded or shed typed RESTARTING —
    the bounded, typed flavor of unavailability."""


@dataclass
class _Direction:
    """Byte accounting for one direction of one connection."""

    buffer: bytearray = field(default_factory=bytearray)  # retained input
    pass_bytes: int = 0
    drop_bytes: int = 0
    need_bytes: int = 0
    inject: bytearray = field(default_factory=bytearray)  # inject slice


class ShimConnection:
    """Client-side connection state + the OnIO application loop."""

    def __init__(self, client: "SidecarClient", conn_id: int):
        self.client = client
        self.conn_id = conn_id
        self.dirs = {False: _Direction(), True: _Direction()}
        self.closed = False
        # True while the retained buffers provably mirror the
        # service's per-conn parse state: every round so far answered
        # OK (or was served by the grant tier, which keeps both sides
        # empty).  Any typed failure, shed, deny or parser error
        # breaks the mirror — the service consumed (or never saw)
        # bytes this side still holds — and the restart replay must
        # then NOT claim RETAINED for this conn.  _reset_fail_closed
        # re-arms it: an emptied shim against a memoryless service is
        # aligned again by construction.
        self.mirror_ok = True

    def on_io(self, reply: bool, data: bytes, end_stream: bool = False,
              deadline_ms: float | None = None) -> tuple[int, bytes]:
        """Feed new input bytes for one direction; returns
        (FilterResult, output bytes to forward downstream).

        Wire contract: every input byte is shipped to the service exactly
        once (the service mirrors the retained buffer and consumes
        already-verdicted overshoot itself); ops returned by the service
        refer to the retained buffer AFTER overshoot consumption, which
        this side reproduces with the pass/drop counters below.

        ``deadline_ms`` (default: the client's configured deadline)
        rides the wire so queue time past it sheds typed instead of
        hanging.  Service loss is fail-closed: retained bytes are
        dropped and SERVICE_UNAVAILABLE returned — never an exception,
        never a hang."""
        d = self.dirs[reply]
        output = bytearray()
        incoming = bytes(data)
        # Captured BEFORE any mutation below: the verdict cache only
        # short-circuits a push that arrived on a fully clean
        # direction (nothing retained, no overshoot counters) so the
        # granted claim covers exactly this payload's whole frames.
        clean_entry = (
            not d.buffer and d.pass_bytes == 0 and d.drop_bytes == 0
        )

        # Apply pre-pass / pre-drop from an earlier verdict that exceeded
        # the then-available input (reference: cilium_proxylib.cc:130-166).
        rest = incoming
        if d.pass_bytes > 0:
            take = min(d.pass_bytes, len(rest))
            output += rest[:take]
            d.pass_bytes -= take
            rest = rest[take:]
        elif d.drop_bytes > 0:
            take = min(d.drop_bytes, len(rest))
            d.drop_bytes -= take
            rest = rest[take:]
        d.buffer += rest

        # Reverse-injected frames go out first, at a frame boundary
        # (reference: cilium_proxylib.cc:186-192).
        if d.inject:
            output += d.inject
            d.inject.clear()

        # Established-flow verdict cache: a granted conn's frame-
        # aligned request push is answered HERE — the bytes never
        # reach the transport (Libra-style: only bytes that NEED
        # inspection cross the seam).  Strictly gated: the direction
        # was fully clean at entry (clean_entry), request direction,
        # and the payload ends at a frame boundary per the GRANT'S OWN
        # framing (CRLF tail, DNS length-prefix walk, ...) — so a
        # revoke at any point leaves the stream parseable from a
        # boundary.  This tier also serves through the restart
        # survival window (the service is down; _grant_valid keeps
        # grants live for restart_grace_s).
        if (
            clean_entry
            and not reply
            and not end_stream
            and incoming
            and self.client._grant_valid(self.conn_id)
            and self.client._grant_frame_aligned(self.conn_id, incoming)
        ):
            del d.buffer[:]  # holds exactly this push (clean_entry)
            output += incoming
            self.client._count_cache_hits(1, len(incoming))
            return int(FilterResult.OK), bytes(output)

        try:
            result, entries = self.client._on_data_rpc(
                self.conn_id, reply, end_stream, incoming,
                deadline_ms=deadline_ms,
            )
        except SidecarRestarting:
            # Fail-closed like SERVICE_UNAVAILABLE below, but typed to
            # the survival window: the caller knows the blackout is
            # bounded by restart_grace_s and retries are cheap.
            d.buffer.clear()
            self.mirror_ok = False
            return int(FilterResult.RESTARTING), bytes(output)
        except (SidecarUnavailable, TimeoutError):
            # Fail-closed: nothing buffered may pass unverdicted while
            # the service is down OR unresponsive past the RPC timeout.
            # (Output assembled so far was authorized by earlier
            # verdicts and still goes out.)
            d.buffer.clear()
            self.mirror_ok = False
            return int(FilterResult.SERVICE_UNAVAILABLE), bytes(output)
        # Queue every entry's ops and inject bytes BEFORE applying any op
        # (mirrors native/shim.cc on_data_rpc): the service splits >16-op
        # verdict lists into continuation entries with all inject bytes
        # attached to the LAST chunk, so an INJECT op in an early chunk
        # must be able to see inject bytes carried by a later one.
        all_ops = []
        for _, res, ops, inj_orig, inj_reply in entries:
            if res != int(FilterResult.OK):
                self.mirror_ok = False
                return res, bytes(output)
            self.dirs[False].inject += inj_orig
            self.dirs[True].inject += inj_reply
            all_ops.extend(ops)
        for op, n in all_ops:
            if n <= 0 and op != MORE:
                self.mirror_ok = False
                return int(FilterResult.PARSER_ERROR), bytes(output)
            if op == MORE:
                d.need_bytes = len(d.buffer) + n
            elif op == PASS:
                take = min(n, len(d.buffer))
                output += d.buffer[:take]
                del d.buffer[:take]
                if n > take:
                    d.pass_bytes = n - take
            elif op == DROP:
                take = min(n, len(d.buffer))
                del d.buffer[:take]
                if n > take:
                    d.drop_bytes = n - take
            elif op == INJECT:
                if n > len(d.inject):
                    self.mirror_ok = False
                    return int(FilterResult.PARSER_ERROR), bytes(output)
                output += d.inject[:n]
                del d.inject[:n]
            elif op == ERROR:
                self.mirror_ok = False
                return int(FilterResult.PARSER_ERROR), bytes(output)
        if result != int(FilterResult.OK):
            self.mirror_ok = False
        return int(result), bytes(output)

    def _reset_fail_closed(self) -> None:
        """After a reconnect the service has no memory of this conn's
        retained bytes; drop them (fail-closed — never forward
        unverdicted residue) and clear the overshoot counters."""
        for d in self.dirs.values():
            d.buffer.clear()
            d.inject.clear()
            d.pass_bytes = d.drop_bytes = d.need_bytes = 0
        # Empty shim vs a service with no memory of the conn: the
        # mirror holds again by construction.
        self.mirror_ok = True

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.client.close_connection(self.conn_id)


class SidecarClient:
    """Wire client: one socket, a reader thread routing replies.

    ``deadline_ms`` > 0 stamps data RPCs with a wire deadline budget;
    ``auto_reconnect`` turns on redial-with-backoff + session replay
    (see module docstring)."""

    def __init__(self, socket_path: str, timeout: float = 10.0,
                 deadline_ms: float = 0.0, auto_reconnect: bool = False,
                 transport: str = TRANSPORT_SOCKET,
                 shm_data_slots: int = 64, shm_slot_bytes: int = 1 << 20,
                 shm_verdict_slots: int = 64,
                 shm_verdict_slot_bytes: int = 1 << 18,
                 flow_cache: bool = True,
                 identity: str = "",
                 shm_oversize_spree: int = 32,
                 restart_grace_s: float = 0.0,
                 restart_queue_frames: int = 0):
        self.socket_path = socket_path
        self.timeout = timeout
        self.deadline_ms = deadline_ms
        self.auto_reconnect = auto_reconnect
        # Fan-in session identity (MSG_SESSION_HELLO): the pod/workload
        # name the service keys admission quotas and per-session
        # shed/quarantine metrics on.  Empty = anonymous (the service
        # quotas under a synthetic per-session name; crash-loop
        # detection needs a stable identity to see the loop).
        self.identity = identity
        # Consecutive data-ring oversize fallbacks before this client
        # demotes its OWN shm rung typed (every frame missing the ring
        # means the fit check is pure overhead).  0 disables.
        self.shm_oversize_spree = shm_oversize_spree
        # Restart survival window (the shim half of hitless restart):
        # on disconnect, instead of tearing the grant table down,
        # shim-local grants keep serving for up to restart_grace_s —
        # the epoch stamp makes this safe (the reconnected service
        # revalidates or revokes every grant during replay).  0 keeps
        # the exact pre-restart behavior (grants die with the socket).
        self.restart_grace_s = restart_grace_s
        # Bound on NON-granted async frames held through the window to
        # be resent (same seq) after replay; past it — or at 0 — such
        # frames are answered immediately with typed RESTARTING sheds.
        self.restart_queue_frames = restart_queue_frames
        self._survival_until = 0.0  # monotonic deadline; 0 = closed
        self.survival_windows = 0
        # Granted-flow pushes answered locally WHILE the service was
        # down — the bench/soak's "traffic served through the
        # blackout" counter (strictly increasing during a restart).
        self.survival_hits = 0
        self.survival_hit_bytes = 0
        self._restart_q: deque = deque()  # (msg_type, parts, seq, ids)
        self._rq_frames = 0
        self._rq_lock = threading.Lock()
        self.restart_shed_frames = 0
        # Cross-restart exactly-once tripwire: delivered-seq ring — a
        # second delivery of a seq still in the ring is counted and
        # SUPPRESSED (never reaches the waiter/callback twice).
        self.double_replies = 0
        self._answered_ring = np.full(1 << 16, -1, np.int64)
        # Cross-session misrouting tripwire: verdict entries delivered
        # to this client for conn ids it NEVER registered.  Asserted 0
        # by the fan-in bench/suites — a nonzero value means a
        # coalesced round's completion fan-out crossed sessions.
        self.misrouted_verdicts = 0
        self._known_conns = np.zeros(0, bool)
        # Established-flow verdict cache, shim half: when True the
        # client opts in (MSG_CACHE_ENABLE) and honors MSG_CACHE_GRANT
        # frames — frame-aligned request pushes for granted conns are
        # answered LOCALLY with the service's own all-allow verdict
        # shape, so uninspected bytes never cross the ring or socket
        # (Libra-style selective copying).  The service only sends
        # grants with its own flow_cache knob on, so service-off is
        # the true baseline regardless of this flag.
        self.flow_cache = flow_cache
        # Grant table: conn-id-indexed epoch/rule arrays (vectorized
        # hit mask for batched sends; grown on demand, -1 = no grant).
        # A grant is live iff its epoch equals the latest service
        # epoch this client has seen (grant/revoke/policy-ack frames
        # all advance it) — the structural invalidation's client half.
        self._grant_epoch = np.empty(0, np.int64)
        self._grant_rule = np.empty(0, np.int32)
        # Per-conn framing code (_FRAMING_CODES; -1 = none): keys the
        # grant's frame-alignment check — CRLF tail vs length-prefix
        # walk — so non-CRLF conns get the local tier too.
        self._grant_framing = np.empty(0, np.int8)
        # Grant-table WRITE lock (reader thread grants/revokes vs the
        # caller-thread close sweep vs the reconnect-loop reset; R19's
        # declared owner for the _grant_* columns).  Reads stay
        # deliberately lock-free: the epoch-equality liveness gate
        # makes a torn READ at worst a missed short-circuit, and the
        # row's data columns are published BEFORE the epoch (the gate)
        # in _on_cache_grant, so a reader that passes _grant_valid
        # never sees another grant's rule/framing.
        self._glock = threading.Lock()
        self._service_epoch = 0
        self.cache_hits = 0
        self.cache_hit_bytes = 0
        # Data-plane bytes actually pushed across the transport (ring
        # or socket) — the flow_cache bench's byte-level proof that
        # cached bytes never crossed the seam.
        self.bytes_pushed = 0
        # Transport preference: "shm" negotiates a pair of lock-free
        # shared-memory rings at session setup (and again after every
        # auto_reconnect replay); ANY negotiation or ring fault falls
        # back to the socket rung typed — the session always serves.
        self.transport_pref = transport
        self._shm_cfg = (shm_data_slots, shm_slot_bytes,
                         shm_verdict_slots, shm_verdict_slot_bytes)
        self._shm: ShmSession | None = None
        self._shm_generation = 0
        self.transport_fallbacks: dict[str, int] = {}
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(socket_path)
        self._seq = itertools.count(1)
        self._wlock = threading.Lock()
        self._pending: dict[int, threading.Event] = {}
        self._verdicts: dict[int, wire.VerdictBatch] = {}
        # Async data rounds sent but not yet answered (seq set), plus
        # the local-answer delivery FIFO: a synthesized cache verdict
        # must never overtake an earlier in-flight round's verdicts
        # for the same conns (the client-side twin of the service
        # tier's completion-FIFO ordering rule).  The bytes still
        # never cross the transport — only the DELIVERY of the local
        # answer waits, queued behind the rounds that were in flight
        # when it was synthesized.
        # seq -> conn_ids: the value is what the disconnect sweep needs
        # to answer a round that died in flight with a typed shed (the
        # cross-restart exactly-once contract's "typed local SHED" arm).
        self._rounds_out: dict[int, np.ndarray | None] = {}
        self._local_q: deque[tuple[set, wire.VerdictBatch]] = deque()
        self._localq_lock = threading.Lock()
        self._control: list[tuple[int, bytes]] = []
        self._control_evt = threading.Event()
        self._clock = threading.Lock()  # serialize control round trips
        self._alive = True
        self._closed = False
        self._down_once = threading.Lock()  # one disconnect hook per drop
        self._down_handled = False
        # Reconnect-loop ownership (guarded by _down_once): exactly one
        # loop may drive recovery at a time.  A disconnect observed
        # while a loop is active (its own replay socket dying, or a
        # just-resumed socket dying before the loop hands off) sets
        # ``pending`` to request another cycle instead of spawning a
        # second loop that would race the first over self.sock.
        self._reconnect_active = False
        self._reconnect_pending = False
        self._reconnected = threading.Event()
        self._reconnected.set()
        self.reconnects = 0
        # Session record for replay: caller-visible module id ->
        # {params, debug, policies payload}; the wire-side id may differ
        # after a service restart, so calls translate through _mod_map.
        self._session_lock = threading.Lock()
        self._modules: dict[int, dict] = {}
        self._mod_map: dict[int, int] = {}
        self._conn_args: dict[int, tuple] = {}
        self._shims: dict[int, ShimConnection] = {}
        # Policy-table epoch from the most recent policy_update ack
        # (-1 before the first update / against a pre-epoch service):
        # the control-plane's handle for "which table generation my
        # rules are serving on" — flowlog records carry the same epoch.
        self.last_policy_epoch = -1
        self._reader = threading.Thread(
            target=self._read_loop, args=(self.sock,), daemon=True
        )
        self._reader.start()
        self.verdict_callback = None  # async mode: called with VerdictBatch
        self._send_hello()
        if transport == TRANSPORT_SHM:
            self._shm_negotiate()
        if flow_cache:
            self._cache_enable()

    def _send_hello(self) -> None:
        """Announce the session identity (fire-and-forget — a legacy
        peer ignores the frame; losing it only costs named metrics and
        crash-loop detection, never serving)."""
        if not self.identity:
            return
        try:
            self._send(
                wire.MSG_SESSION_HELLO,
                wire.pack_session_hello(self.identity),
            )
        except (SidecarUnavailable, OSError):
            pass

    # -- plumbing ---------------------------------------------------------

    @property
    def connected(self) -> bool:
        return self._alive

    def _read_loop(self, sock: socket.socket) -> None:
        # The socket is passed in, never re-read from self.sock: the
        # reader must bind to the socket its spawner owned — by first
        # bytecode a later reconnect cycle may already have swapped
        # self.sock, and two readers on one socket would race frames.
        try:
            reader = wire.BufferedReader(sock)
            while True:
                msg_type, payload = reader.recv_msg()
                if msg_type == wire.MSG_VERDICT_BATCH:
                    self._deliver_verdict(wire.unpack_verdict_batch(payload))
                elif msg_type == wire.MSG_VERDICT_MULTI:
                    for vb in wire.unpack_verdict_multi(payload):
                        self._deliver_verdict(vb)
                elif msg_type == wire.MSG_SHM_CREDIT:
                    self._on_shm_credit(payload)
                elif msg_type == wire.MSG_CACHE_GRANT:
                    self._on_cache_grant(payload)
                elif msg_type == wire.MSG_CACHE_REVOKE:
                    self._on_cache_revoke(payload)
                elif msg_type == wire.MSG_CONN_RESULT:
                    # Reader-ordered stale-grant retirement for conn-id
                    # reuse: grants the service wrote BEFORE this
                    # registration reply were applied above, and the
                    # fresh registration grant is sent AFTER the reply
                    # — dropping the row here (same thread, socket
                    # order) retires exactly the stale ones.
                    if len(payload) >= 8:
                        self._grant_drop(
                            int(np.frombuffer(payload[:8], "<u8", 1)[0])
                        )
                    self._control.append((msg_type, payload))
                    self._control_evt.set()
                elif msg_type in (wire.MSG_HANDOFF,
                                  wire.MSG_HANDOFF_REPLY):
                    # Restart handoff is a service-to-service side
                    # channel (a successor dials its predecessor); a
                    # shim session must never see either half.  Dropped
                    # typed here — routing one into the control slot
                    # would hand an RPC waiter a reply it never asked
                    # for.
                    log.warning(
                        "unexpected handoff frame %d on a shim "
                        "session; dropped", msg_type,
                    )
                else:
                    self._control.append((msg_type, payload))
                    self._control_evt.set()
        except (wire.ConnectionClosed, OSError):
            pass
        finally:
            self._on_disconnect(sock)

    def _on_disconnect(self, sock: socket.socket | None = None) -> None:
        """Socket died: fail every waiter typed-and-immediately, then
        (optionally) start the reconnect loop.  ``sock`` identifies the
        DYING socket: a reader whose socket is no longer self.sock is
        reporting a replay attempt that was already torn down and
        superseded — a delayed callback from it must be a no-op, or it
        would mark a healthy reconnected client down, fail its waiters,
        and spawn a rival reconnect loop that replays the session again
        and orphans the healthy socket with a live reader."""
        with self._down_once:
            # Identity checked UNDER the latch lock: _resume performs
            # its swap + down-state reset atomically under this same
            # lock, so a stale callback preempted between an outside
            # check and the latch could otherwise interleave with a
            # successful replay and mark the fresh session down.
            if sock is not None and sock is not self.sock:
                return
            if self._down_handled:
                return
            self._down_handled = True
            self._alive = False
        self._reconnected.clear()
        # Restart survival window: with a grace budget and a reconnect
        # loop to revalidate behind it, grants OUTLIVE the socket —
        # granted flows keep serving locally through the blackout.
        # The epoch stamp makes this safe: the reconnected service
        # re-grants (or silently does not) every replayed conn, and
        # the MSG_CONN_RESULT handler drops each conn's row at replay,
        # so a stale grant can never outlive its revalidation.
        # Without the window, grants die with the session exactly as
        # before (the service has no successor-memory of them).
        if self.restart_grace_s > 0 and self.auto_reconnect and (
            not self._closed
        ):
            self._survival_until = (
                time.monotonic() + self.restart_grace_s
            )
            self.survival_windows += 1
        else:
            self._reset_grants()
        # Frames held for a resend die with this (second) disconnect:
        # clear the queue FIRST — their seqs are still registered in
        # _rounds_out and the sweep below answers each exactly once
        # (typed); leaving them queued would resend them after a later
        # replay and double-reply.
        with self._rq_lock:
            self._restart_q.clear()
            self._rq_frames = 0
        # The shm session dies with the socket (a fresh one is
        # negotiated after replay): deactivate FIRST so no new pushes
        # land, then wake the waiters — ring in-flight RPCs share the
        # same _pending sweep and fail typed like socket in-flights.
        sess = self._shm
        if sess is not None:
            self._shm = None
            sess.active = False
        # Wake data waiters WITHOUT a verdict: they observe the missing
        # entry and raise SidecarUnavailable instead of sleeping out
        # their full RPC timeout.
        for seq, evt in list(self._pending.items()):
            self._pending.pop(seq, None)
            evt.set()
        # Async rounds lost with the socket will never be answered by
        # the service — answer each HERE with a typed SHED batch (the
        # exactly-once contract: every seq in flight at death gets
        # exactly one answer — old process, new process, or this typed
        # local shed; silence is never an option).  Then flush the
        # ordering FIFO: queued local answers were decided under
        # grants that were live at synthesis, and the rounds they
        # waited on are now answered, so they deliver in synthesis
        # order.
        with self._localq_lock:
            dead_rounds = sorted(self._rounds_out.items())
            self._rounds_out.clear()
        for seq, cids in dead_rounds:
            self._deliver_verdict(self._shed_batch(seq, cids))
        with self._localq_lock:
            flushed = [lvb for _, lvb in self._local_q]
            self._local_q.clear()
        for lvb in flushed:
            self._deliver_verdict(lvb)
        self._control_evt.set()
        if sess is not None:
            try:
                sess.destroy()
            except Exception:  # noqa: BLE001 — release is best-effort
                log.exception("shm teardown on disconnect failed")
        if self.auto_reconnect and not self._closed:
            with self._down_once:
                if self._reconnect_active:
                    # A loop is already driving recovery: this is its
                    # own replay socket dying (service restarted again
                    # mid-replay) or a just-resumed socket dying before
                    # the loop exited.  Request another cycle — a
                    # second loop would race the first over self.sock,
                    # replaying the session twice and orphaning the
                    # loser's socket with a live reader.
                    self._reconnect_pending = True
                    return
                self._reconnect_active = True
            try:
                threading.Thread(
                    target=self._reconnect_loop,
                    daemon=True,
                    name="sidecar-reconnect",
                ).start()
            except RuntimeError:  # can't start new thread
                # Un-register, or auto-reconnect is latched off for the
                # life of the process: every later disconnect would see
                # an "active" loop that never existed and just set
                # pending.
                log.exception("failed to spawn sidecar reconnect loop")
                with self._down_once:
                    self._reconnect_active = False

    def _raise_down(self) -> None:
        """Typed dead-service raise: RESTARTING while the survival
        window is open (bounded blackout), plain unavailability else."""
        if self._survival_open():
            raise SidecarRestarting(
                f"verdict service at {self.socket_path} is restarting"
            )
        raise SidecarUnavailable(
            f"verdict service at {self.socket_path} is down"
        )

    def _send(self, msg_type: int, payload: bytes) -> None:
        if not self._alive:
            self._raise_down()
        with self._wlock:
            sock = self.sock
            try:
                # lint: disable=R2 -- _wlock exists to serialize frame writes; the OSError path runs _teardown so a wedged peer cannot hold the lock past the write timeout
                wire.send_msg(sock, msg_type, payload)
            except OSError as e:
                # Tear down only the socket we actually wrote to:
                # _resume may have swapped in a fresh one concurrently,
                # and killing it would throw away the just-replayed
                # session.  A write error need not coincide with a FIN
                # reaching the reader (ETIMEDOUT against a wedged-but-
                # open peer), so a bare close would leave the reader
                # parked in recv — no _on_disconnect, no reconnect loop,
                # client wedged forever.
                if sock is self.sock:
                    _teardown(sock)  # force the reader out of recv
                raise SidecarUnavailable(str(e)) from e

    # -- shm transport (sidecar/shm.py, sidecar/transport.py) -------------

    @property
    def transport_mode(self) -> str:
        sess = self._shm
        return (
            TRANSPORT_SHM if sess is not None and sess.active
            else TRANSPORT_SOCKET
        )

    def _transport_fallback(self, reason: str, n: int = 1) -> None:
        self.transport_fallbacks[reason] = (
            self.transport_fallbacks.get(reason, 0) + n
        )
        metrics.SidecarTransportFallback.inc(reason, amount=n)

    def transport_status(self) -> dict:
        """Client-side transport telemetry (the shim half of
        `cilium sidecar status`'s transport section)."""
        sess = self._shm
        out = {
            "mode": self.transport_mode,
            "preference": self.transport_pref,
            "fallbacks": dict(self.transport_fallbacks),
            "bytes_pushed": self.bytes_pushed,
            # Shim half of the verdict cache: locally answered pushes
            # and the bytes that never crossed the seam because of
            # them.
            "cache": {
                "enabled": self.flow_cache,
                "hits": self.cache_hits,
                "hit_bytes": self.cache_hit_bytes,
                "service_epoch": self._service_epoch,
            },
            # Restart survival window: shim-local serving while the
            # sidecar is away, plus the exactly-once tripwires.
            "restart": {
                "grace_s": self.restart_grace_s,
                "windows": self.survival_windows,
                "window_open": self._survival_open_peek(),
                "survival_hits": self.survival_hits,
                "survival_hit_bytes": self.survival_hit_bytes,
                "queued_frames": self._rq_frames,
                "shed_frames": self.restart_shed_frames,
                "double_replies": self.double_replies,
            },
        }
        if sess is not None:
            out["session"] = sess.status()
        return out

    def _shm_negotiate(self) -> bool:
        """Create a fresh ring pair and offer it to the service
        (MSG_SHM_ATTACH).  Every failure is contained: the session
        stays on the socket rung, typed and counted — never raises."""
        self._shm_generation += 1
        ds, db, vs, vb = self._shm_cfg
        try:
            sess = ShmSession.create(self._shm_generation, ds, db, vs, vb)
        except Exception:  # noqa: BLE001 — no /dev/shm, quota, ...
            log.exception("shm ring creation failed; socket transport")
            self._transport_fallback(REASON_ATTACH_REJECTED)
            return False
        req = sess.attach_request()
        try:
            got = self._control_rpc(
                lambda: (wire.MSG_SHM_ATTACH, json.dumps(req).encode()),
                wire.MSG_SHM_ATTACH_REPLY,
                retry=False,
            )
            rep = json.loads(got.decode())
            status = int(rep.get("status", -1))
            if status != int(FilterResult.OK):
                raise wire.WireError(
                    rep.get("error") or f"attach status {status}"
                )
        except Exception:  # noqa: BLE001 — old service, reject, timeout
            log.warning(
                "shm attach rejected; serving on the socket transport",
                exc_info=True,
            )
            sess.destroy()
            self._transport_fallback(REASON_ATTACH_REJECTED)
            return False
        self._shm = sess
        # Segment lease the service granted: after an abrupt death the
        # survivor unlinks this session's segments once it expires.
        try:
            sess.lease_s = float(rep.get("lease_s") or 0.0)
        except (TypeError, ValueError):
            sess.lease_s = 0.0
        log.info(
            "shm transport attached (generation %s, %dx%dB data slots, "
            "lease %.1fs)", rep.get("generation"), ds, db, sess.lease_s,
        )
        return True

    # -- verdict cache, shim half (policy/invariance.py contract) ----------

    _GRANT_MAX = 1 << 22  # conn ids beyond this keep the normal path

    def _cache_enable(self) -> None:
        """One-time opt-in (fire-and-forget): tells the service this
        shim understands MSG_CACHE_GRANT/REVOKE frames.  Best-effort —
        a lost enable only costs the local short-circuit."""
        try:
            self._send(wire.MSG_CACHE_ENABLE, wire.pack_cache_enable())
        except (SidecarUnavailable, OSError):
            pass

    def _grant_ensure(self, conn_id: int) -> bool:
        if conn_id >= self._GRANT_MAX:
            return False
        n = len(self._grant_epoch)
        if conn_id >= n:
            new = max(4096, n)
            while new <= conn_id:
                new *= 2
            ge = np.full(new, -1, np.int64)
            ge[:n] = self._grant_epoch
            gr = np.full(new, -1, np.int32)
            gr[:n] = self._grant_rule
            gf = np.full(new, -1, np.int8)
            gf[:n] = self._grant_framing
            self._grant_epoch = ge
            self._grant_rule = gr
            self._grant_framing = gf
        return True

    def _on_cache_grant(self, payload: bytes) -> None:
        conn_id, epoch, rule, flags, framing = wire.unpack_cache_grant(
            payload
        )
        if not self.flow_cache or not flags & wire.CACHE_FLAG_ALLOW:
            return
        code = _FRAMING_CODES.get(framing)
        if code is None:
            # A framing this shim build does not know: ignore the
            # grant (the normal path serves — forward compatible).
            return
        if epoch > self._service_epoch:
            self._service_epoch = epoch
        with self._glock:
            if self._grant_ensure(conn_id):
                # Publish order matters for the lock-free readers: the
                # data columns (rule, framing) land BEFORE the epoch —
                # the epoch-equality check in _grant_valid is the
                # liveness gate, so a reader must never pass the gate
                # and then read a previous grant's rule/framing.
                self._grant_rule[conn_id] = rule
                self._grant_framing[conn_id] = code
                self._grant_epoch[conn_id] = GRANT_PROTOCOL.guard(
                    GRANT_NONE, GRANT_ARMED, epoch
                )

    def _on_cache_revoke(self, payload: bytes) -> None:
        epoch = wire.unpack_cache_revoke(payload)
        if epoch > self._service_epoch:
            # Every grant under an older epoch is now structurally
            # dead (the hit mask compares equality) — no sweep needed.
            self._service_epoch = epoch

    def _grant_drop(self, conn_id: int) -> None:
        with self._glock:
            if conn_id < len(self._grant_epoch):
                # Tombstone the gate FIRST, then the data columns: the
                # reverse of the grant publish order, so a concurrent
                # lock-free reader never passes the epoch gate on a
                # half-dropped row.
                self._grant_epoch[conn_id] = GRANT_PROTOCOL.require_edges(
                    (GRANT_ARMED, GRANT_NONE), GRANT_NONE
                )
                self._grant_rule[conn_id] = -1
                self._grant_framing[conn_id] = -1

    def _reset_grants(self) -> None:
        """A (re)connected service has no memory of this session's
        grants; drop them all (fail-safe: the normal path serves)."""
        with self._glock:
            self._grant_epoch.fill(
                GRANT_PROTOCOL.require_edges(
                    (GRANT_ARMED, GRANT_NONE), GRANT_NONE
                )
            )
            self._grant_rule.fill(-1)
            self._grant_framing.fill(-1)

    def _count_cache_hits(self, n: int, nbytes: int) -> None:
        self.cache_hits += n
        self.cache_hit_bytes += nbytes
        if not self._alive:
            # Served locally THROUGH a blackout: the hitless-restart
            # proof counter (strictly increasing while the service is
            # down, asserted by the soak and the restart bench).
            self.survival_hits += n
            self.survival_hit_bytes += nbytes
            metrics.SidecarSurvivalHits.inc(amount=n)
        metrics.VerdictCacheHits.inc("shim", amount=n)

    # -- restart survival window ------------------------------------------

    def _survival_open(self) -> bool:
        """True while the restart survival window is open.  The FIRST
        check past the deadline closes it lazily: grants reset and any
        held frames shed typed — traffic drives the expiry, no timer
        thread (same idiom as the session-quarantine lazy heal)."""
        until = self._survival_until
        if until == 0.0:
            return False
        if time.monotonic() < until:
            return True
        self._survival_until = 0.0
        self._reset_grants()
        self._shed_restart_queue()
        return False

    def _survival_open_peek(self) -> bool:
        """Side-effect-free read for status surfaces."""
        return (
            self._survival_until > 0.0
            and time.monotonic() < self._survival_until
        )

    def _restart_enqueue(self, msg_type: int, parts, seq: int,
                         ids) -> bool:
        """Hold one non-granted async round through the window for a
        same-seq resend after replay.  False = no room (the caller
        owes the round a typed RESTARTING shed)."""
        n = len(ids) if ids is not None else 1
        with self._rq_lock:
            if self._rq_frames + n > self.restart_queue_frames:
                return False
            self._restart_q.append((msg_type, parts, seq, ids))
            self._rq_frames += n
        return True

    def _shed_restart_queue(self) -> None:
        """Answer every held round with a typed RESTARTING shed (window
        expired, or replay superseded) — never silently dropped."""
        with self._rq_lock:
            items = list(self._restart_q)
            self._restart_q.clear()
            self._rq_frames = 0
        for _mt, _parts, seq, ids in items:
            self.restart_shed_frames += len(ids) if ids is not None else 1
            self._deliver_verdict(
                self._shed_batch(seq, ids, int(FilterResult.RESTARTING))
            )

    def _flush_restart_queue(self) -> None:
        """Replay completed: resend every held round with its ORIGINAL
        seq (the resumed service answers it once — the exactly-once
        contract's "new process" arm).  A send that fails here sheds
        typed; the round never goes unanswered."""
        with self._rq_lock:
            items = list(self._restart_q)
            self._restart_q.clear()
            self._rq_frames = 0
        for msg_type, parts, seq, ids in items:
            try:
                self._transport_send(
                    msg_type, parts, seq=seq, conn_ids=ids
                )
            except SidecarUnavailable:
                self.restart_shed_frames += (
                    len(ids) if ids is not None else 1
                )
                self._deliver_verdict(
                    self._shed_batch(
                        seq, ids, int(FilterResult.RESTARTING)
                    )
                )

    def _grant_valid(self, conn_id: int) -> bool:
        return (
            self.flow_cache
            and (self._alive or self._survival_open())
            and conn_id < len(self._grant_epoch)
            and self._grant_epoch[conn_id] == self._service_epoch
            and self._service_epoch >= 0
        )

    def _grant_frame_aligned(self, conn_id: int, data: bytes) -> bool:
        """Whole-frame check under the grant's own framing (the caller
        verified _grant_valid, so the row and its framing code are
        live)."""
        if conn_id >= len(self._grant_framing):
            return False
        code = int(self._grant_framing[conn_id])
        if code < 0:
            return False
        return _FRAMING_BY_CODE[code].payload_aligned(data)

    def _cached_batch(self, seq: int, ids: np.ndarray,
                      lengths) -> wire.VerdictBatch:
        """A locally synthesized all-allow verdict batch — byte-for-
        byte the service's `_verdict_body` shape for an all-allow
        round: per entry (PASS frame_len, MORE 1), result OK, no
        inject."""
        n = len(ids)
        ops = np.zeros(2 * n, wire.FILTER_OP)
        ops["op"][0::2] = int(PASS)
        ops["n_bytes"][0::2] = np.asarray(lengths, np.int64)
        ops["op"][1::2] = int(MORE)
        ops["n_bytes"][1::2] = 1
        zeros = np.zeros(n, "<u4")
        return wire.VerdictBatch(
            seq,
            np.ascontiguousarray(ids, "<u8"),
            np.full(n, int(FilterResult.OK), "<u4"),
            np.full(n, 2, "<u4"),
            zeros,
            zeros,
            ops,
            b"",
        )

    def _cache_try_local(self, seq: int, ids: np.ndarray, lengths,
                         tail_ok) -> bool:
        """Answer one whole batch locally when EVERY entry is granted
        under the live epoch and frame-aligned — the bytes never cross
        the transport.  Partial hits keep the normal path (the
        service's Phase-A mask owns per-entry splitting).  ``tail_ok``
        is a thunk taking the int64 conn ids and returning the
        per-entry frame-alignment mask (keyed per entry on the grant's
        own framing), evaluated only after every cheap grant-table
        check has passed — the common no-grants case (cache off
        service-side) never pays the O(payload) scan."""
        if not self.flow_cache or not len(ids):
            return False
        if not self._alive and not self._survival_open():
            # Dead service, window closed (or just lazily expired —
            # _survival_open reset the grants): the normal path owes
            # the caller its typed failure.
            return False
        # Range-check the RAW u64 ids before the int64 view: a wire id
        # >= 2^63 would wrap negative and fancy-index the wrong grant
        # rows (same guard as the service's conn-table lanes).
        if int(ids.max()) >= len(self._grant_epoch):
            return False
        cids = ids.astype(np.int64)
        if not (self._grant_epoch[cids] == self._service_epoch).all():
            return False
        if not tail_ok(cids).all():
            return False
        nbytes = int(np.asarray(lengths, np.int64).sum())
        self._count_cache_hits(len(ids), nbytes)
        vb = self._cached_batch(seq, ids, lengths)
        # Ordering: a synthesized answer must never overtake a round
        # still in flight (its verdicts could carry ops for the same
        # conns).  The bytes never cross either way; when anything is
        # outstanding — or the FIFO already holds an earlier local
        # answer — the delivery queues behind it and _round_settled
        # releases it in synthesis order.
        with self._localq_lock:
            waits = set(self._rounds_out)
            waits.update(self._pending)
            queued = bool(waits or self._local_q)
            if queued:
                self._local_q.append((waits, vb))
        if not queued:
            self._deliver_verdict(vb)
        return True

    def _blob_tail_ok(self, blob: bytes, lens: np.ndarray,
                      cids: np.ndarray) -> np.ndarray:
        """Frame-alignment mask for a packed blob batch — the service's
        `_cache_item_hits` gate: a blob inconsistent with its lengths
        reads as a miss (never indexes past the buffer), else every
        segment must end at a frame boundary under ITS OWN grant's
        framing.  The all-CRLF batch (the overwhelmingly common case)
        keeps the single vectorized scan."""
        if len(blob) != int(lens.sum()):
            return np.zeros(len(lens), bool)
        u8 = np.frombuffer(blob, np.uint8)
        starts = np.concatenate(([0], np.cumsum(lens)))[:-1]
        codes = self._grant_framing[cids]
        if (codes == _CODE_CRLF).all():
            return segments_end_crlf(u8, starts, lens)
        out = np.zeros(len(lens), bool)
        for code in np.unique(codes):
            if code < 0:
                continue  # no framing on record: miss
            m = codes == code
            out[m] = _FRAMING_BY_CODE[int(code)].segments_aligned(
                u8, starts[m], lens[m]
            )
        return out

    def _rows_aligned(self, rows: np.ndarray, lens: np.ndarray,
                      cids: np.ndarray) -> np.ndarray:
        """Per-framing twin of _blob_tail_ok for the fixed-width
        matrix layout."""
        codes = self._grant_framing[cids]
        if (codes == _CODE_CRLF).all():
            return rows_end_crlf(rows, lens)
        out = np.zeros(len(lens), bool)
        for code in np.unique(codes):
            if code < 0:
                continue
            m = codes == code
            out[m] = _FRAMING_BY_CODE[int(code)].rows_aligned(
                rows[m], lens[m]
            )
        return out

    def detach_shm(self) -> None:
        """Gracefully return the session to the socket transport (call
        when quiescent: in-flight ring verdicts should have drained).
        Fault paths demote without this round trip."""
        sess = self._shm
        if sess is None:
            return
        with self._wlock:
            if self._shm is not sess:
                return
            sess.active = False
            self._shm = None
        try:
            self._control_rpc(
                lambda: (
                    wire.MSG_SHM_DETACH,
                    wire.pack_shm_detach(sess.generation),
                ),
                wire.MSG_ACK,
                retry=False,
            )
        except (SidecarUnavailable, TimeoutError, wire.WireError):
            pass  # socket teardown releases the mappings anyway
        try:
            sess.destroy()
        except Exception:  # noqa: BLE001
            log.exception("shm teardown on detach failed")

    def _transport_send(self, msg_type: int, payload,
                        seq: int | None = None, conn_ids=None) -> None:
        """Data-plane send: ride the shm data ring when attached (one
        scatter-gather slot write + at most one doorbell frame), fall
        back to a full socket frame per-batch when the ring is full or
        the frame oversized — never blocks on ring space, never spins.

        ``payload`` may be a list of buffers: the ring path writes them
        straight into the slot (the bulk rows/blob part is never
        re-materialized); only the socket fallback joins them."""
        if self._alive and not self._reconnected.is_set():
            # Session replay in progress on the fresh socket: the
            # successor adopts handed-off conns lazily as the replay
            # re-registers them, so a data frame racing the replay
            # would surface UNKNOWN_CONNECTION for a conn the caller
            # legitimately holds.  Typed-restarting instead: the
            # caller's round is held for a same-seq resend after the
            # replay (or shed typed RESTARTING) — never misanswered.
            raise SidecarRestarting(
                f"verdict service at {self.socket_path} is replaying"
            )
        nbytes = (
            sum(len(p) for p in payload)
            if isinstance(payload, (list, tuple)) else len(payload)
        )
        # Transport byte accounting (ring or socket, before any
        # fallback split): the flow_cache bench's byte-level proof —
        # a cache-on run must push strictly fewer bytes than its
        # cache-off control.
        self.bytes_pushed += nbytes
        sess = self._shm
        if sess is None or not sess.active:
            self._send(msg_type, _join(payload))
            return
        if not self._alive:
            self._raise_down()
        reason = None
        pushed = False
        spree = False
        with self._wlock:
            if sess.active and self._shm is sess:
                if not sess.data.fits(nbytes):
                    reason = REASON_OVERSIZE
                    sess.oversize_run += 1
                    spree = bool(
                        self.shm_oversize_spree
                        and sess.oversize_run >= self.shm_oversize_spree
                    )
                else:
                    pos = sess.data.tail
                    if sess.data.try_push(msg_type, payload,
                                          sess.credit_head):
                        if seq is not None:
                            sess.inflight[seq] = (pos, conn_ids)
                        sess.counters.data_frames += 1
                        sess.oversize_run = 0
                        # lint: disable=R2 -- the doorbell frame must publish under the same lock as the ring push (SPSC + ordering); SO_SNDTIMEO/_teardown bound a wedged peer exactly as in _send
                        self._shm_doorbell_locked(sess)
                        pushed = True
                    else:
                        reason = REASON_RING_FULL
        if pushed:
            # Credit-piggybacked verdict polling: a data push is the
            # natural boundary to sweep verdicts the service already
            # committed to the ring — elides the credit-frame RTT from
            # the verdict path at small batches (outside the write
            # lock: delivery callbacks may send, which retakes it).
            # Contained: the push already succeeded, and an embedder
            # callback raising out of the sweep must not surface as a
            # failed send (a retry would double-submit the seq).
            try:
                self.poll_shm_verdicts()
            except Exception:  # noqa: BLE001 — delivery error only
                log.exception("piggyback verdict sweep failed")
            return
        if reason is not None:
            self._transport_fallback(reason)
        if spree:
            # Every frame this session pushes misses the ring: stop
            # paying the fit check and serve on the socket rung, typed.
            # served_through uses the same freshest lower bound as the
            # mirror-poll demotion (admitted frames keep their promised
            # verdicts; never-admitted ones are answered typed SHED).
            self._demote_shm(
                REASON_OVERSIZE_SPREE,
                served_through=max(sess.credit_head, sess.data.head),
            )
        self._send(msg_type, _join(payload))

    def _shm_doorbell_locked(self, sess: ShmSession) -> None:
        """Doorbell (write lock held): ring the bell for any un-belled
        tail.  The service also rechecks the ring's tail mirror after
        every drain, so a doorbell is a wakeup, never load-bearing —
        under backlog many frames coalesce into one drain (the batched
        half), while an idle service is woken immediately (suppressing
        the bell until the next credit measured ~1ms of p99 bubble at
        100k/s)."""
        tail = sess.data.tail
        if tail <= sess.db_tail:
            return
        self._doorbell_send(sess, tail)

    def _doorbell_send(self, sess: ShmSession, tail: int) -> None:
        payload = wire.pack_shm_doorbell(
            sess.generation, tail, sess.v_head
        )
        sess.counters.doorbell(tail - sess.db_tail)
        sess.db_tail = tail
        sess.v_head_sent = sess.v_head
        sock = self.sock
        try:
            wire.send_msg(sock, wire.MSG_SHM_DOORBELL, payload)
        except OSError as e:
            # Same teardown contract as _send: only kill the socket we
            # wrote to, and force the reader out of recv.
            if sock is self.sock:
                _teardown(sock)
            raise SidecarUnavailable(str(e)) from e

    def _deliver_verdict(self, vb: wire.VerdictBatch,
                         sess: "ShmSession | None" = None) -> None:
        """Route one verdict batch (socket frame, verdict ring, or a
        demotion-synthesized SHED) to its waiter or the async
        callback — THE one delivery path for every transport.
        ``sess`` names the session whose ring produced this verdict:
        a ring drain must pop ITS OWN session's inflight entry (the
        exactly-once claim the demotion sweep checks), never a re-read
        self._shm that a concurrent demotion may already have
        cleared."""
        if sess is None:
            sess = self._shm
        if sess is not None:
            sess.inflight.pop(vb.seq, None)
        # Cross-restart exactly-once tripwire: a seq must be answered
        # ONCE — by the old process, the new process, or a typed local
        # shed.  A second delivery (e.g. a shed raced by a late real
        # verdict across the restart boundary) is counted and
        # suppressed so the waiter/callback never observes it.
        slot = vb.seq & (len(self._answered_ring) - 1)
        if self._answered_ring[slot] == vb.seq:
            self.double_replies += 1
            log.error(
                "double reply suppressed for seq %d (%d entries)",
                vb.seq, vb.count,
            )
            return
        self._answered_ring[slot] = vb.seq
        self._check_misroute(vb)
        cb = self.verdict_callback
        evt = self._pending.pop(vb.seq, None)
        if evt is not None:
            self._verdicts[vb.seq] = vb
            evt.set()
        elif cb is not None:
            cb(vb)
        # AFTER this round's own delivery: release any queued local
        # cache answers it was holding back (they were synthesized
        # later, so they must land later).
        self._round_settled(vb.seq)

    _KNOWN_MAX = 1 << 22  # tripwire coverage cap (mirrors _GRANT_MAX)

    def _mark_known_conn(self, conn_id: int) -> None:
        """Session-lifetime record of every conn id this client ever
        registered — the cross-session misrouting tripwire's ground
        truth (closed conns STAY marked so a verdict in flight at close
        never reads as a misroute)."""
        if conn_id >= self._KNOWN_MAX:
            return
        n = len(self._known_conns)
        if conn_id >= n:
            new = max(4096, n)
            while new <= conn_id:
                new *= 2
            arr = np.zeros(new, bool)
            arr[:n] = self._known_conns
            self._known_conns = arr
        self._known_conns[conn_id] = True

    def _check_misroute(self, vb: wire.VerdictBatch) -> None:
        """Count verdict entries for conn ids this session NEVER
        registered: one vectorized mask per delivered batch.  Zero is
        the fan-in contract (a coalesced device round's completion
        fan-out must route every slice back to its own session);
        asserted in-bench and by the fault suites.  A session with NO
        registered conns still counts (a fully-misrouted slice to a
        fresh session must not read as zero), and a shim that sends
        data for conns it never registered trips this too — both sides
        of the register-before-send contract are violations."""
        if not vb.count:
            return
        kn = self._known_conns
        ids = vb.conn_ids
        small = ids[ids < self._KNOWN_MAX].astype(np.int64)
        if not len(small):
            return
        oob = small >= len(kn)
        bad = int(oob.sum()) + int((~kn[small[~oob]]).sum())
        if bad:
            self.misrouted_verdicts += bad
            log.error(
                "cross-session misroute: %d verdict entries for conn "
                "ids this session never registered (seq %d)",
                bad, vb.seq,
            )

    def _round_settled(self, seq: int | None) -> None:
        """One round stopped being in flight (verdict delivered, RPC
        timeout, failed send).  Retire its seq from the ordering FIFO
        and deliver — in synthesis order — any queued local cache
        answers that no longer wait on anything."""
        release: list[wire.VerdictBatch] = []
        with self._localq_lock:
            if seq is not None:
                self._rounds_out.pop(seq, None)
                for waits, _ in self._local_q:
                    waits.discard(seq)
            while self._local_q and not self._local_q[0][0]:
                release.append(self._local_q.popleft()[1])
        for lvb in release:
            self._deliver_verdict(lvb)

    def _shm_forget(self, seq: int) -> None:
        sess = self._shm
        if sess is not None:
            sess.inflight.pop(seq, None)

    @staticmethod
    def _shed_batch(seq: int, conn_ids,
                    result: int = int(FilterResult.SHED)
                    ) -> wire.VerdictBatch:
        """A synthesized typed verdict batch (SHED by default,
        RESTARTING for survival-window sheds) — byte-for-byte the
        entry shape the service's shed path produces, used when frames
        the service never admitted must be answered locally (zero
        silent loss on demotion, disconnect, or window expiry)."""
        cids = np.ascontiguousarray(
            conn_ids if conn_ids is not None else [], "<u8"
        )
        n = len(cids)
        zeros = np.zeros(n, "<u4")
        return wire.VerdictBatch(
            seq,
            cids,
            np.full(n, result, "<u4"),
            zeros,
            zeros,
            zeros,
            np.zeros(0, wire.FILTER_OP),
            b"",
        )

    def _on_shm_credit(self, payload: bytes) -> None:
        """Reader-thread half of the shm protocol: drain the verdict
        ring through the credited tail, absorb data-ring credit, honor
        a quarantine demotion, and re-bell coalesced pushes."""
        sess = self._shm
        if sess is None:
            return
        generation, flags, data_head, v_tail = wire.unpack_shm_credit(
            payload
        )
        if generation != sess.generation:
            return  # stale credit from a superseded session
        sess.counters.credits += 1
        try:
            self._drain_verdict_ring(sess, v_tail)
        except RingError:
            log.exception("verdict ring corrupt; demoting to socket")
            # The service keeps consuming between this credit's
            # data_head and now — the data ring's head mirror is the
            # fresher lower bound (see poll_shm_verdicts).
            self._demote_shm(
                REASON_TORN_SLOT,
                served_through=max(data_head, sess.data.head),
            )
            return
        sess.credit_head = data_head
        if flags & CREDIT_FLAG_QUARANTINED:
            self._demote_shm(REASON_TORN_SLOT, served_through=data_head)
            return
        with self._wlock:
            if sess.active and self._shm is sess:
                if sess.data.tail > sess.db_tail:
                    # Pushes landed while the service drained: re-bell.
                    # lint: disable=R2 -- the re-bell must pair with the cursor state it publishes under this lock; SO_SNDTIMEO bounds a wedge (same contract as _send)
                    self._doorbell_send(sess, sess.data.tail)
                elif (
                    sess.v_head - sess.v_head_sent
                    >= sess.verdict.slots // 2
                ):
                    # Refresh the service's verdict-ring credit before
                    # its producer view saturates.
                    # lint: disable=R2 -- see the re-bell above; a pure credit refresh rides the same bounded doorbell write
                    self._doorbell_send(sess, sess.db_tail)

    def _drain_verdict_ring(self, sess: ShmSession, v_tail: int) -> int:
        """Consume committed verdict frames through ``v_tail`` — THE
        one drain loop, shared by the credit handler (reader thread)
        and the mirror poll below; ``drain_lock`` serializes them so
        the ring keeps a single logical consumer.  Raises RingError on
        a torn slot (caller owns the demotion)."""
        drained = 0
        with sess.drain_lock:
            if not sess.active:
                # A demotion completed its SHED sweep (which runs
                # under this same lock) between our session capture
                # and here: the ring may already be destroyed and
                # every undelivered seq was answered typed — draining
                # now would double-reply and read freed memory.
                return 0
            while sess.v_head < v_tail:
                msg_type, frame, _t = sess.verdict.read(sess.v_head)
                sess.v_head += 1
                sess.verdict.set_head(sess.v_head)
                sess.counters.verdict_frames += 1
                drained += 1
                if msg_type == wire.MSG_VERDICT_BATCH:
                    self._deliver_verdict(
                        wire.unpack_verdict_batch(frame), sess=sess
                    )
                elif msg_type == wire.MSG_VERDICT_MULTI:
                    for vb in wire.unpack_verdict_multi(frame):
                        self._deliver_verdict(vb, sess=sess)
                else:
                    raise RingError(
                        f"unexpected verdict-ring frame type {msg_type}"
                    )
        return drained

    def poll_shm_verdicts(self) -> int:
        """Credit-piggybacked verdict polling: drain any verdict
        frames ALREADY COMMITTED to the shm ring, discovered through
        the post-commit tail mirror, without waiting for the service's
        MSG_SHM_CREDIT socket frame.  At small batches the credit hop
        dominated the verdict RTT (ROADMAP item 3); a pipelined shim
        calling this at its natural boundaries — every data push does
        it automatically — takes verdicts off the ring the moment they
        are committed, so the credit frame degrades to a no-op wakeup
        exactly like the doorbell on the service side.  Never a spin:
        this is one mirror read at an event that was happening anyway
        (lint R2.2 stays clean — no loop waits on the mirror to move).
        The mirror is safe to act on because the producer stores it
        strictly AFTER the slot commit word (shm.ShmRing.try_push), and
        every slot is still commit-word-validated on read.  Returns the
        number of frames drained."""
        sess = self._shm
        if sess is None or not sess.active:
            return 0
        tail = sess.verdict.tail
        if tail <= sess.v_head:
            return 0
        sess.counters.mirror_drains += 1
        try:
            drained = self._drain_verdict_ring(sess, tail)
        except RingError:
            log.exception("verdict ring corrupt (mirror poll); demoting")
            # served_through must be the freshest lower bound on the
            # service's data-ring consumption or admitted frames get
            # BOTH a synthesized SHED and their promised post-detach
            # socket verdict.  Mirror polling means credit frames (and
            # their data_head) can lag arbitrarily, but the service
            # stores the data ring's head MIRROR after every frame it
            # copies out — strictly fresher, same trust domain as the
            # tail mirror this poll just consumed.
            self._demote_shm(
                REASON_TORN_SLOT,
                served_through=max(sess.credit_head, sess.data.head),
            )
            return 0
        sess.counters.mirror_frames += drained
        return drained

    def _demote_shm(self, reason: str,
                    served_through: int | None = None) -> None:
        """Demote the session to the socket transport, typed: ring
        frames the service never admitted (position >=
        ``served_through``) are answered here with synthesized SHED
        batches — zero silent loss; admitted frames keep their real
        verdicts, which now arrive as socket frames."""
        sess = self._shm
        if sess is None:
            return
        with self._wlock:
            if self._shm is not sess:
                return
            sess.active = False
            self._shm = None
            # Tell the service to latch off the rings NOW (fire-and-
            # forget: this runs on the reader thread, which cannot wait
            # a control round trip, hence the no-ack flag).  Without
            # it, a CLIENT-detected fault (torn verdict slot) leaves
            # the service's peer active, writing verdicts into a ring
            # nobody drains — every admitted in-flight RPC would time
            # out instead of getting its promised socket verdict.
            try:
                # lint: disable=R2 -- one bounded fire-and-forget frame under the write lock, same contract as the doorbell sends
                wire.send_msg(
                    self.sock, wire.MSG_SHM_DETACH,
                    wire.pack_shm_detach(
                        sess.generation, wire.DETACH_FLAG_NO_ACK
                    ),
                )
            except OSError:
                pass  # socket death tears the mappings down anyway
        self._transport_fallback(reason)
        log.warning(
            "shm transport demoted to socket (%s); %d ring frames "
            "in flight", reason, len(sess.inflight),
        )
        # The SHED sweep serializes with the verdict-ring drains: the
        # mirror poll made the drain a second-thread affair, so a
        # concurrent drain could deliver seq X's real verdict while
        # this sweep still holds X in its snapshot — a double reply.
        # Under drain_lock (taken WITHOUT _wlock — the session is
        # already detached above, so no new drain can start) any
        # in-progress drain finishes its deliveries first, and each
        # seq is then CLAIMED by an atomic pop: whoever pops delivers,
        # exactly once.
        with sess.drain_lock:
            pending = sorted(sess.inflight.keys())
            for seq in pending:
                ent = sess.inflight.pop(seq, None)
                if ent is None:
                    continue  # a racing drain already delivered it
                pos, cids = ent
                if served_through is not None and pos < served_through:
                    continue  # admitted: its verdict arrives on the socket
                self._deliver_verdict(self._shed_batch(seq, cids))
        try:
            sess.destroy()
        except Exception:  # noqa: BLE001 — release is best-effort
            log.exception("shm teardown on demotion failed")

    # -- reconnect --------------------------------------------------------

    def _reconnect_loop(self) -> None:
        try:
            self._reconnect_cycles()
        except Exception:  # noqa: BLE001 — never die still registered
            # The loop owns _reconnect_active; dying with it set would
            # latch auto-reconnect off for the life of the process
            # (every later disconnect would just set pending).  Clear
            # it so the next disconnect can spawn a fresh loop.
            log.exception("sidecar reconnect loop died")
            with self._down_once:
                self._reconnect_active = False

    def _reconnect_cycles(self) -> None:
        backoff = Exponential(
            min_duration=0.05, max_duration=2.0, name="sidecar-reconnect"
        )
        while not self._closed:
            with self._down_once:
                # A disconnect latched during the previous cycle is
                # consumed by this fresh attempt.
                self._reconnect_pending = False
            try:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(self.socket_path)
            except OSError:
                backoff.wait()
                continue
            try:
                self._resume(sock)
            except Exception:  # noqa: BLE001 — service mid-restart
                log.exception("sidecar session replay failed; retrying")
                self._alive = False
                # Tear the attempt down; the reader _resume started (if
                # it got that far) dies on the shut socket and its
                # _on_disconnect fails waiters typed-and-immediately —
                # it cannot spawn a rival loop (this one is still
                # registered active; the disconnect just sets
                # _reconnect_pending, cleared at the top of the retry).
                # A replay socket that died MID-replay already ran the
                # same _on_disconnect before the RPC failure landed us
                # here.
                _teardown(sock)
                backoff.wait()
                continue
            with self._down_once:
                pending = self._reconnect_pending
                if not pending:
                    self._reconnect_active = False
            if pending:
                # The just-resumed socket already died (its reader
                # latched a disconnect between replay completion and
                # this handoff): run another cycle rather than exiting
                # with nobody driving recovery — but back off first
                # like the other failure paths, or a flapping service
                # gets hammered with back-to-back full session replays.
                backoff.wait()
                continue
            return
        with self._down_once:
            self._reconnect_active = False

    def _resume(self, sock: socket.socket) -> None:
        """Swap in the fresh socket and replay the session: modules,
        their last-acked policies, then registered connections.  Shim
        buffers reset fail-closed (the service has no memory of them)."""
        with self._wlock:
            if self._closed:
                # close() raced the reconnect: never leave a "closed"
                # client holding a live session.
                _teardown(sock)
                raise wire.WireError("client closed during reconnect")
            # Swap + down-state reset as ONE atomic unit under the
            # disconnect latch lock: _on_disconnect validates its dying
            # socket's identity under the same lock, so a stale
            # callback observes either the old socket (and latches the
            # old session down, correctly) or the new one (and no-ops)
            # — never a half-applied swap that lets it mark the fresh
            # session dead.  (_wlock -> _down_once nesting occurs only
            # here; _down_once holders never take _wlock.)
            with self._down_once:
                self.sock = sock
                self._alive = True
                self._down_handled = False
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,), daemon=True
        )
        self._reader.start()
        # Re-announce identity FIRST: the replayed session's quotas,
        # metrics and reconnect-storm accounting must key on the same
        # pod name as the original (this hello is also what lets the
        # service SEE a crash loop).
        self._send_hello()
        if self.flow_cache:
            # Opt back in BEFORE the conn replay so the restarted
            # service grants replayed conns as they register (old
            # grants were dropped at disconnect).
            self._cache_enable()
        with self._session_lock:
            modules = dict(self._modules)
            conn_args = dict(self._conn_args)
            shims = dict(self._shims)
        for caller_id, rec in modules.items():
            wire_id = self._raw_open_module(rec["params"], rec["debug"])
            self._mod_map[caller_id] = wire_id
            if rec["policies"] is not None:
                status = self._raw_policy_update(wire_id, rec["policies"])
                if status != int(FilterResult.OK):
                    raise wire.WireError(
                        f"policy replay rejected: {status}"
                    )
        restored: set[int] = set()
        for conn_id, args in conn_args.items():
            # RETAINED claim: this shim's retained-buffer mirror
            # survived the blackout intact (no round failed typed on
            # it), so a warm successor may adopt the predecessor's
            # mid-frame residue for the conn — the two sides then
            # resume the identical parse state.
            shim = shims.get(conn_id)
            cflags = (
                wire.CONN_FLAG_RETAINED
                if shim is not None and shim.mirror_ok
                else 0
            )
            res, rflags = self._raw_new_connection(conn_id, args, cflags)
            if res != int(FilterResult.OK):
                log.warning(
                    "conn %d replay rejected: %d", conn_id, res
                )
            elif rflags & wire.CONN_RESULT_FLAG_RESIDUE_ADOPTED:
                restored.add(conn_id)
        for conn_id, shim in shims.items():
            # A conn whose residue the successor ADOPTED keeps its
            # retained buffer and overshoot counters: the service
            # mirror matches them byte for byte, so a frame split
            # across the restart reassembles instead of being dropped.
            # Every other conn resets fail-closed exactly as before —
            # empty shim, empty (or discarded) service state, aligned.
            if conn_id not in restored:
                shim._reset_fail_closed()
        if self._closed:
            # close() raced the replay AFTER the initial check passed:
            # it may have shut the OLD socket just before the swap, so
            # the session we just replayed would outlive the "closed"
            # client (live reader thread until process exit).  Tear the
            # fresh socket down here; the reader exits on the closed
            # socket.  (close() runs lock-free by design — taking
            # _wlock there could deadlock behind a sendall wedged on a
            # stuck peer, the very thing close() must break.)
            _teardown(self.sock)
            raise wire.WireError("client closed during reconnect")
        if self.transport_pref == TRANSPORT_SHM:
            # Fresh rings for the fresh session: the restarted service
            # has no memory of the old segments (and must never attach
            # a stale one — generation bumps every negotiation).  A
            # failed negotiation leaves the session serving on the
            # socket rung; _shm_negotiate never raises.
            self._shm_negotiate()
        # Restart survival window closes on a completed replay: the
        # grants the replay re-armed are live again (a conn whose replay
        # was rejected already had its grant row dropped by the
        # MSG_CONN_RESULT handler).  Epoch sync is downgrade-safe for
        # the same reason.  Held restart-window frames resend LAST,
        # after every conn exists service-side, under their ORIGINAL
        # seqs — exactly-once from the caller's view.
        self._survival_until = 0.0
        if self.last_policy_epoch >= 0:
            self._service_epoch = self.last_policy_epoch
        # Un-gate the data plane BEFORE the flush: _transport_send
        # holds mid-replay rounds typed while _reconnected is clear,
        # and the flush's own same-seq resends must pass it.
        self._reconnected.set()
        self._flush_restart_queue()
        self.reconnects += 1
        metrics.SidecarClientReconnects.inc()
        log.info(
            "sidecar client reconnected to %s (%d modules, %d conns, "
            "transport=%s)",
            self.socket_path, len(modules), len(conn_args),
            self.transport_mode,
        )

    def _wire_mod(self, module_id: int) -> int:
        return self._mod_map.get(module_id, module_id)

    def _control_rpc(self, build, want: int, retry: bool = True) -> bytes:
        """One control round trip.  ``build()`` produces (msg_type,
        payload) — re-invoked on retry so module ids re-translate after
        a replay.  Control RPCs are idempotent at the service, so ONE
        retry after an auto-reconnect is safe (transport errors on
        non-idempotent ops would never be blindly retried — the data
        plane fails closed instead)."""
        for attempt in (0, 1):
            try:
                with self._clock:
                    self._control_evt.clear()
                    msg_type, payload = build()
                    # lint: disable=R2 -- _clock serializes the control request/response pairing (one outstanding RPC by design); _send fails typed+fast on a dead socket and its own _wlock wedge handling is bounded
                    self._send(msg_type, payload)
                    if not self._control_evt.wait(self.timeout):
                        if not self._alive:
                            raise SidecarUnavailable("connection lost")
                        raise TimeoutError("no control reply")
                    if not self._control:
                        # Woken by _on_disconnect, not by a reply.
                        raise SidecarUnavailable("connection lost")
                    got_type, got = self._control.pop(0)
                    if got_type != want:
                        raise wire.WireError(
                            f"expected {want}, got {got_type}"
                        )
                    return got
            except SidecarUnavailable:
                if not (
                    retry
                    and self.auto_reconnect
                    and attempt == 0
                    and not self._closed
                ):
                    raise
                if not self._reconnected.wait(self.timeout):
                    raise
        raise SidecarUnavailable("unreachable")  # not reached

    # -- module / policy surface (the libcilium.h analog) -----------------

    def _raw_open_module(self, params, debug: bool) -> int:
        got = self._control_rpc(
            lambda: (
                wire.MSG_OPEN_MODULE,
                wire.pack_open_module(params or [], debug),
            ),
            wire.MSG_MODULE_ID,
            retry=False,
        )
        return int(np.frombuffer(got, "<u8", 1)[0])

    def open_module(self, params: list[tuple[str, str]] | None = None,
                    debug: bool = False) -> int:
        got = self._control_rpc(
            lambda: (
                wire.MSG_OPEN_MODULE,
                wire.pack_open_module(params or [], debug),
            ),
            wire.MSG_MODULE_ID,
        )
        mod = int(np.frombuffer(got, "<u8", 1)[0])
        with self._session_lock:
            self._modules[mod] = {
                "params": list(params or []), "debug": debug,
                "policies": None,
            }
            self._mod_map[mod] = mod
        return mod

    def status(self) -> dict:
        """Service counters (MSG_STATUS round trip)."""
        got = self._control_rpc(
            lambda: (wire.MSG_STATUS, b""), wire.MSG_STATUS_REPLY
        )
        return json.loads(got.decode())

    def trace(self, n: int = 100, kind: str | None = None,
              session: int | None = None) -> dict:
        """Latency-trace dump (MSG_TRACE round trip): the service's
        most recent sampled spans / slow exemplars plus its per-stage
        latency aggregate — the `cilium sidecar trace` surface.
        ``session`` filters spans to one fan-in session."""
        req: dict = {"n": int(n)}
        if kind:
            req["kind"] = kind
        if session is not None:
            req["session"] = int(session)
        got = self._control_rpc(
            lambda: (wire.MSG_TRACE, json.dumps(req).encode()),
            wire.MSG_TRACE_REPLY,
        )
        return json.loads(got.decode())

    def timeline(self, n: int = 100, since: int = 0,
                 table: str | None = None) -> dict:
        """Flight-recorder dump (MSG_TIMELINE round trip): the declared-
        edge incident timeline, occupancy buckets, and postmortem
        summaries — the `cilium sidecar timeline` surface.  ``since``
        filters to events with seq strictly greater (incremental tail);
        ``table`` pins one typestate table."""
        req: dict = {"n": int(n), "since": int(since)}
        if table:
            req["table"] = table
        got = self._control_rpc(
            lambda: (wire.MSG_TIMELINE, json.dumps(req).encode()),
            wire.MSG_TIMELINE_REPLY,
        )
        return json.loads(got.decode())

    def ledger(self, n: int = 100, since: int = 0,
               cause: str | None = None) -> dict:
        """Device-economics dump (MSG_LEDGER round trip): the compile
        ledger (per-cause trace/compile events), batch-formation
        provenance, and the resident-executable census — the `cilium
        sidecar ledger` surface.  ``since`` filters to events with seq
        strictly greater (incremental tail); ``cause`` pins one compile
        cause (cold/prewarm/churn-new-shape/...)."""
        req: dict = {"n": int(n), "since": int(since)}
        if cause:
            req["cause"] = cause
        got = self._control_rpc(
            lambda: (wire.MSG_LEDGER, json.dumps(req).encode()),
            wire.MSG_LEDGER_REPLY,
        )
        return json.loads(got.decode())

    def observe(self, n: int = 100, verdict: str | None = None,
                path: str | None = None, rule: int | None = None,
                conn: int | None = None,
                since: int | None = None,
                epoch: int | None = None,
                session: int | None = None) -> dict:
        """Flow-record query (MSG_OBSERVE round trip): the service's
        per-flow verdict records with device-side rule attribution —
        the `cilium observe` surface.  ``since`` is the follow cursor
        (records with seq > since, ascending); ``epoch`` filters on the
        policy-table epoch the verdict was decided against; ``session``
        on the fan-in shim session the conn registered through."""
        req: dict = {"n": int(n)}
        if verdict is not None:
            req["verdict"] = verdict
        if path is not None:
            req["path"] = path
        if rule is not None:
            req["rule"] = int(rule)
        if conn is not None:
            req["conn"] = int(conn)
        if since is not None:
            req["since"] = int(since)
        if epoch is not None:
            req["epoch"] = int(epoch)
        if session is not None:
            req["session"] = int(session)
        got = self._control_rpc(
            lambda: (wire.MSG_OBSERVE, json.dumps(req).encode()),
            wire.MSG_OBSERVE_REPLY,
        )
        return json.loads(got.decode())

    def _raw_policy_update(self, wire_mod: int, payload: bytes) -> int:
        got = self._control_rpc(
            lambda: (
                wire.MSG_POLICY_UPDATE,
                wire.pack_policy_update(wire_mod, payload),
            ),
            wire.MSG_ACK,
            retry=False,
        )
        status, epoch = wire.unpack_ack_epoch(got)
        if status == int(FilterResult.OK) and epoch >= 0:
            self.last_policy_epoch = epoch
            if epoch > self._service_epoch:
                self._service_epoch = epoch
        return status

    def policy_update(self, module_id: int, policies) -> int:
        payload = json.dumps([asdict(p) for p in policies]).encode()
        got = self._control_rpc(
            lambda: (
                wire.MSG_POLICY_UPDATE,
                wire.pack_policy_update(self._wire_mod(module_id), payload),
            ),
            wire.MSG_ACK,
        )
        status, epoch = wire.unpack_ack_epoch(got)
        if status == int(FilterResult.OK):
            if epoch >= 0:
                self.last_policy_epoch = epoch
                if epoch > self._service_epoch:
                    self._service_epoch = epoch
            with self._session_lock:
                if module_id in self._modules:
                    self._modules[module_id]["policies"] = payload
        return status

    def _raw_new_connection(
        self, conn_id: int, args: tuple, flags: int = 0,
    ) -> tuple[int, int]:
        """Replay-path registration; returns ``(result,
        result_flags)``.  ``flags`` carries the RETAINED claim; the
        reply's trailing flags word (absent on an old service — treated
        as 0) reports whether handoff residue was adopted."""
        (module_id, proto, ingress, src_id, dst_id,
         src_addr, dst_addr, policy_name) = args
        got = self._control_rpc(
            lambda: (
                wire.MSG_NEW_CONNECTION,
                wire.pack_new_connection(
                    self._wire_mod(module_id), conn_id, ingress, src_id,
                    dst_id, proto, src_addr, dst_addr, policy_name,
                    flags,
                ),
            ),
            wire.MSG_CONN_RESULT,
            retry=False,
        )
        res = int(np.frombuffer(got[8:12], "<u4", 1)[0])
        rflags = (
            int(np.frombuffer(got[12:16], "<u4", 1)[0])
            if len(got) >= 16 else 0
        )
        return res, rflags

    def new_connection(
        self,
        module_id: int,
        proto: str,
        conn_id: int,
        ingress: bool,
        src_id: int,
        dst_id: int,
        src_addr: str,
        dst_addr: str,
        policy_name: str,
    ) -> tuple[int, ShimConnection | None]:
        args = (module_id, proto, ingress, src_id, dst_id,
                src_addr, dst_addr, policy_name)
        got = self._control_rpc(
            lambda: (
                wire.MSG_NEW_CONNECTION,
                wire.pack_new_connection(
                    self._wire_mod(module_id), conn_id, ingress, src_id,
                    dst_id, proto, src_addr, dst_addr, policy_name,
                ),
            ),
            wire.MSG_CONN_RESULT,
        )
        res = int(np.frombuffer(got[8:], "<u4", 1)[0])
        if res != int(FilterResult.OK):
            return res, None
        shim = ShimConnection(self, conn_id)
        with self._session_lock:
            self._conn_args[conn_id] = args
            self._shims[conn_id] = shim
        self._mark_known_conn(conn_id)
        return res, shim

    def close_connection(self, conn_id: int) -> None:
        with self._session_lock:
            self._conn_args.pop(conn_id, None)
            self._shims.pop(conn_id, None)
        self._grant_drop(conn_id)
        try:
            self._send(wire.MSG_CLOSE, wire.pack_close(conn_id))
        except SidecarUnavailable:
            pass  # the restart already forgot the conn

    def close(self) -> None:
        self._closed = True
        # Capture the socket OBJECT once: _resume swaps self.sock on
        # reconnect, and a re-read between shutdown and close could
        # shutdown the old socket but bare-close the new one —
        # recreating the lingering-reader leak for the fresh reader.
        # (_resume checks _closed after the swap and tears the fresh
        # socket down the same way.)
        _teardown(self.sock)
        sess = self._shm
        self._shm = None
        if sess is not None:
            sess.active = False
            try:
                sess.destroy()
            except Exception:  # noqa: BLE001 — release is best-effort
                log.exception("shm teardown on close failed")

    # -- data plane -------------------------------------------------------

    def _on_data_rpc(self, conn_id: int, reply: bool, end_stream: bool,
                     data: bytes, deadline_ms: float | None = None):
        """Synchronous single-entry round trip (the OnData ABI call).
        NEVER retried across a reconnect (see retry classification);
        raises SidecarUnavailable immediately on a dead service."""
        seq = next(self._seq)
        flags = (wire.FLAG_REPLY if reply else 0) | (
            wire.FLAG_END_STREAM if end_stream else 0
        )
        evt = threading.Event()
        self._pending[seq] = evt
        budget_ms = self.deadline_ms if deadline_ms is None else deadline_ms
        if budget_ms and budget_ms > 0:
            payload = wire.pack_data_batch_dl(
                int(budget_ms * 1000.0), seq, [conn_id], [flags],
                [len(data)], data,
            )
            msg = wire.MSG_DATA_BATCH_DL
        else:
            payload = wire.pack_data_batch(
                seq, [conn_id], [flags], [len(data)], data
            )
            msg = wire.MSG_DATA_BATCH
        try:
            self._transport_send(
                msg, payload, seq=seq,
                conn_ids=np.asarray([conn_id], np.uint64),
            )
        except SidecarUnavailable:
            self._pending.pop(seq, None)
            self._shm_forget(seq)
            self._round_settled(seq)
            raise
        if not evt.wait(self.timeout):
            self._pending.pop(seq, None)
            self._shm_forget(seq)
            # A timed-out RPC will never deliver: local answers queued
            # behind it must not wait forever.
            self._round_settled(seq)
            raise TimeoutError("no verdict reply")
        vb = self._verdicts.pop(seq, None)
        if vb is None:
            # Woken by _on_disconnect: the service died mid-flight.
            raise SidecarUnavailable("connection lost awaiting verdict")
        entries = [vb.entry(i) for i in range(vb.count)]
        result = entries[-1][1] if entries else int(FilterResult.OK)
        return result, entries

    def _send_round(self, msg_type: int, parts, seq: int,
                    ids: np.ndarray) -> None:
        """Send one async data round with its seq registered in
        ``_rounds_out`` BEFORE any bytes move — the cache tier's
        ordering gate must see the round in flight from the instant it
        can be answered.  A failed send retires the seq (no verdict
        will ever come to retire it) — EXCEPT inside the restart
        survival window, where the round is either held bounded for a
        same-seq resend after replay or answered right here with a
        typed RESTARTING shed; the caller sees success either way (the
        answer arrives through the normal delivery path, exactly
        once)."""
        with self._localq_lock:
            self._rounds_out[seq] = ids
        try:
            self._transport_send(msg_type, parts, seq=seq, conn_ids=ids)
        except SidecarRestarting:
            if self._restart_enqueue(msg_type, parts, seq, ids):
                return  # held: resent (same seq) after the replay
            self.restart_shed_frames += len(ids)
            self._deliver_verdict(
                self._shed_batch(seq, ids, int(FilterResult.RESTARTING))
            )
        except BaseException:
            self._round_settled(seq)
            raise

    def send_batch(self, seq: int, conn_ids, flags, lengths, blob: bytes) -> None:
        """Async batched mode (latency bench): fire a DATA batch; replies
        arrive on verdict_callback.  A batch whose every entry is
        request-direction, frame-aligned, and cache-granted is answered
        locally — nothing crosses the transport."""
        ids = np.ascontiguousarray(conn_ids, "<u8")
        if self.flow_cache:
            fl = np.asarray(flags, np.uint8)
            lens = np.asarray(lengths, np.int64)
            if not fl.any() and self._cache_try_local(
                seq, ids, lens,
                lambda cids: self._blob_tail_ok(blob, lens, cids),
            ):
                return
        parts = wire.pack_data_batch_parts(seq, ids, flags, lengths, blob)
        self._send_round(wire.MSG_DATA_BATCH, parts, seq, ids)

    def send_matrix(self, seq: int, width: int, conn_ids, lengths,
                    rows_bytes: bytes, complete: bool = False) -> None:
        """Fixed-width pre-padded batch (request direction): the service
        reshapes straight into the device layout.  ``complete=True``
        declares every row is exactly one whole frame (the edge owns
        framing), letting the service skip its per-row content scan.
        A fully cache-granted, frame-aligned matrix is answered
        locally — the rows never cross the transport."""
        ids = np.ascontiguousarray(conn_ids, "<u8")
        if self.flow_cache and len(ids):
            li = np.asarray(lengths, np.int64)

            def _tail_ok(cids, n=len(ids)):
                # The framing's rows_aligned owns the width bound (a
                # malformed length reads as a miss); a rows buffer
                # inconsistent with (n, width) reads as a miss too.
                if width < 1 or len(rows_bytes) != n * width:
                    return np.zeros(n, bool)
                rows = np.frombuffer(rows_bytes, np.uint8).reshape(n, width)
                return self._rows_aligned(rows, li, cids)

            if self._cache_try_local(seq, ids, li, _tail_ok):
                return
        # Scatter-gather parts (wire.py owns the layout): the rows
        # buffer (the bulk) goes into the ring slot (or one sendall)
        # without an intermediate join.
        parts = wire.pack_data_matrix_parts(
            seq, width, ids, lengths, rows_bytes,
            wire.MAT_FLAG_COMPLETE if complete else 0,
        )
        self._send_round(wire.MSG_DATA_MATRIX, parts, seq, ids)

    def send_blob(self, seq: int, conn_ids, lengths, blob: bytes) -> None:
        """Compact request-direction batch: exact payload bytes only
        (the service builds the device row view with an on-device
        gather).  Preferred over send_matrix when the device link is
        bandwidth-limited — the wire and uplink carry no padding.
        Fully cache-granted frame-aligned batches are answered locally
        (see send_batch)."""
        ids = np.ascontiguousarray(conn_ids, "<u8")
        if self.flow_cache and len(ids):
            lens = np.asarray(lengths, np.int64)
            if self._cache_try_local(
                seq, ids, lens,
                lambda cids: self._blob_tail_ok(blob, lens, cids),
            ):
                return
        # Scatter-gather parts (wire.py owns the layout — see
        # send_matrix): the blob rides into the slot without a join.
        parts = wire.pack_data_batch_parts(
            seq, ids, np.zeros(len(ids), np.uint8), lengths, blob
        )
        self._send_round(wire.MSG_DATA_BATCH, parts, seq, ids)
