"""Datapath-side shim: per-connection buffering + the OnIO contract.

The Python twin of the native C++ shim (``native/shim.cc``): connects to
the verdict service, registers connections, ships byte batches, and
applies returned FilterOps to its buffers with the exact byte-accounting
semantics of the reference's Envoy-side consumer
(reference: envoy/cilium_proxylib.cc:125-214 GoFilter::Instance::OnIO —
pre-pass/pre-drop counters, need_bytes gating, reverse-direction inject
output, INJECT from the per-direction inject slice, ≤16 ops applied per
round with continuation).

Used by tests (op/byte parity against the in-process oracle) and by the
latency bench (batched async mode).
"""

from __future__ import annotations

import itertools
import json
import socket
import threading
from dataclasses import asdict, dataclass, field

import numpy as np

from ..proxylib.types import DROP, ERROR, INJECT, MORE, PASS, FilterResult
from . import wire


@dataclass
class _Direction:
    """Byte accounting for one direction of one connection."""

    buffer: bytearray = field(default_factory=bytearray)  # retained input
    pass_bytes: int = 0
    drop_bytes: int = 0
    need_bytes: int = 0
    inject: bytearray = field(default_factory=bytearray)  # inject slice


class ShimConnection:
    """Client-side connection state + the OnIO application loop."""

    def __init__(self, client: "SidecarClient", conn_id: int):
        self.client = client
        self.conn_id = conn_id
        self.dirs = {False: _Direction(), True: _Direction()}
        self.closed = False

    def on_io(self, reply: bool, data: bytes, end_stream: bool = False) -> tuple[int, bytes]:
        """Feed new input bytes for one direction; returns
        (FilterResult, output bytes to forward downstream).

        Wire contract: every input byte is shipped to the service exactly
        once (the service mirrors the retained buffer and consumes
        already-verdicted overshoot itself); ops returned by the service
        refer to the retained buffer AFTER overshoot consumption, which
        this side reproduces with the pass/drop counters below."""
        d = self.dirs[reply]
        output = bytearray()
        incoming = bytes(data)

        # Apply pre-pass / pre-drop from an earlier verdict that exceeded
        # the then-available input (reference: cilium_proxylib.cc:130-166).
        rest = incoming
        if d.pass_bytes > 0:
            take = min(d.pass_bytes, len(rest))
            output += rest[:take]
            d.pass_bytes -= take
            rest = rest[take:]
        elif d.drop_bytes > 0:
            take = min(d.drop_bytes, len(rest))
            d.drop_bytes -= take
            rest = rest[take:]
        d.buffer += rest

        # Reverse-injected frames go out first, at a frame boundary
        # (reference: cilium_proxylib.cc:186-192).
        if d.inject:
            output += d.inject
            d.inject.clear()

        result, entries = self.client._on_data_rpc(
            self.conn_id, reply, end_stream, incoming
        )
        # Queue every entry's ops and inject bytes BEFORE applying any op
        # (mirrors native/shim.cc on_data_rpc): the service splits >16-op
        # verdict lists into continuation entries with all inject bytes
        # attached to the LAST chunk, so an INJECT op in an early chunk
        # must be able to see inject bytes carried by a later one.
        all_ops = []
        for _, res, ops, inj_orig, inj_reply in entries:
            if res != int(FilterResult.OK):
                return res, bytes(output)
            self.dirs[False].inject += inj_orig
            self.dirs[True].inject += inj_reply
            all_ops.extend(ops)
        for op, n in all_ops:
            if n <= 0 and op != MORE:
                return int(FilterResult.PARSER_ERROR), bytes(output)
            if op == MORE:
                d.need_bytes = len(d.buffer) + n
            elif op == PASS:
                take = min(n, len(d.buffer))
                output += d.buffer[:take]
                del d.buffer[:take]
                if n > take:
                    d.pass_bytes = n - take
            elif op == DROP:
                take = min(n, len(d.buffer))
                del d.buffer[:take]
                if n > take:
                    d.drop_bytes = n - take
            elif op == INJECT:
                if n > len(d.inject):
                    return int(FilterResult.PARSER_ERROR), bytes(output)
                output += d.inject[:n]
                del d.inject[:n]
            elif op == ERROR:
                return int(FilterResult.PARSER_ERROR), bytes(output)
        return int(result), bytes(output)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.client.close_connection(self.conn_id)


class SidecarClient:
    """Wire client: one socket, a reader thread routing replies."""

    def __init__(self, socket_path: str, timeout: float = 10.0):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(socket_path)
        self.timeout = timeout
        self._seq = itertools.count(1)
        self._wlock = threading.Lock()
        self._pending: dict[int, threading.Event] = {}
        self._verdicts: dict[int, wire.VerdictBatch] = {}
        self._control: list[tuple[int, bytes]] = []
        self._control_evt = threading.Event()
        self._clock = threading.Lock()  # serialize control round trips
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self.verdict_callback = None  # async mode: called with VerdictBatch

    # -- plumbing ---------------------------------------------------------

    def _read_loop(self) -> None:
        reader = wire.BufferedReader(self.sock)
        try:
            while True:
                msg_type, payload = reader.recv_msg()
                if msg_type == wire.MSG_VERDICT_BATCH:
                    vb = wire.unpack_verdict_batch(payload)
                    cb = self.verdict_callback
                    evt = self._pending.pop(vb.seq, None)
                    if evt is not None:
                        self._verdicts[vb.seq] = vb
                        evt.set()
                    elif cb is not None:
                        cb(vb)
                elif msg_type == wire.MSG_VERDICT_MULTI:
                    cb = self.verdict_callback
                    for vb in wire.unpack_verdict_multi(payload):
                        evt = self._pending.pop(vb.seq, None)
                        if evt is not None:
                            self._verdicts[vb.seq] = vb
                            evt.set()
                        elif cb is not None:
                            cb(vb)
                else:
                    self._control.append((msg_type, payload))
                    self._control_evt.set()
        except (wire.ConnectionClosed, OSError):
            pass

    def _control_rpc(self, msg_type: int, payload: bytes, want: int) -> bytes:
        with self._clock:
            self._control_evt.clear()
            with self._wlock:
                wire.send_msg(self.sock, msg_type, payload)
            if not self._control_evt.wait(self.timeout):
                raise TimeoutError("no control reply")
            got_type, got = self._control.pop(0)
            if got_type != want:
                raise wire.WireError(f"expected {want}, got {got_type}")
            return got

    # -- module / policy surface (the libcilium.h analog) -----------------

    def open_module(self, params: list[tuple[str, str]] | None = None,
                    debug: bool = False) -> int:
        got = self._control_rpc(
            wire.MSG_OPEN_MODULE,
            wire.pack_open_module(params or [], debug),
            wire.MSG_MODULE_ID,
        )
        return int(np.frombuffer(got, "<u8", 1)[0])

    def status(self) -> dict:
        """Service counters (MSG_STATUS round trip)."""
        got = self._control_rpc(wire.MSG_STATUS, b"", wire.MSG_STATUS_REPLY)
        return json.loads(got.decode())

    def policy_update(self, module_id: int, policies) -> int:
        payload = json.dumps([asdict(p) for p in policies]).encode()
        got = self._control_rpc(
            wire.MSG_POLICY_UPDATE,
            wire.pack_policy_update(module_id, payload),
            wire.MSG_ACK,
        )
        return wire.unpack_ack(got)

    def new_connection(
        self,
        module_id: int,
        proto: str,
        conn_id: int,
        ingress: bool,
        src_id: int,
        dst_id: int,
        src_addr: str,
        dst_addr: str,
        policy_name: str,
    ) -> tuple[int, ShimConnection | None]:
        got = self._control_rpc(
            wire.MSG_NEW_CONNECTION,
            wire.pack_new_connection(
                module_id, conn_id, ingress, src_id, dst_id,
                proto, src_addr, dst_addr, policy_name,
            ),
            wire.MSG_CONN_RESULT,
        )
        res = int(np.frombuffer(got[8:], "<u4", 1)[0])
        if res != int(FilterResult.OK):
            return res, None
        return res, ShimConnection(self, conn_id)

    def close_connection(self, conn_id: int) -> None:
        with self._wlock:
            wire.send_msg(self.sock, wire.MSG_CLOSE, wire.pack_close(conn_id))

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- data plane -------------------------------------------------------

    def _on_data_rpc(self, conn_id: int, reply: bool, end_stream: bool,
                     data: bytes):
        """Synchronous single-entry round trip (the OnData ABI call)."""
        seq = next(self._seq)
        flags = (wire.FLAG_REPLY if reply else 0) | (
            wire.FLAG_END_STREAM if end_stream else 0
        )
        evt = threading.Event()
        self._pending[seq] = evt
        payload = wire.pack_data_batch(
            seq, [conn_id], [flags], [len(data)], data
        )
        with self._wlock:
            wire.send_msg(self.sock, wire.MSG_DATA_BATCH, payload)
        if not evt.wait(self.timeout):
            self._pending.pop(seq, None)
            raise TimeoutError("no verdict reply")
        vb = self._verdicts.pop(seq)
        entries = [vb.entry(i) for i in range(vb.count)]
        result = entries[-1][1] if entries else int(FilterResult.OK)
        return result, entries

    def send_batch(self, seq: int, conn_ids, flags, lengths, blob: bytes) -> None:
        """Async batched mode (latency bench): fire a DATA batch; replies
        arrive on verdict_callback."""
        payload = wire.pack_data_batch(seq, conn_ids, flags, lengths, blob)
        with self._wlock:
            wire.send_msg(self.sock, wire.MSG_DATA_BATCH, payload)

    def send_matrix(self, seq: int, width: int, conn_ids, lengths,
                    rows_bytes: bytes, complete: bool = False) -> None:
        """Fixed-width pre-padded batch (request direction): the service
        reshapes straight into the device layout.  ``complete=True``
        declares every row is exactly one whole frame (the edge owns
        framing), letting the service skip its per-row content scan."""
        payload = wire.pack_data_matrix(
            seq, width, conn_ids, lengths, rows_bytes,
            wire.MAT_FLAG_COMPLETE if complete else 0,
        )
        with self._wlock:
            wire.send_msg(self.sock, wire.MSG_DATA_MATRIX, payload)

    def send_blob(self, seq: int, conn_ids, lengths, blob: bytes) -> None:
        """Compact request-direction batch: exact payload bytes only
        (the service builds the device row view with an on-device
        gather).  Preferred over send_matrix when the device link is
        bandwidth-limited — the wire and uplink carry no padding."""
        payload = wire.pack_data_batch(
            seq, conn_ids, [0] * len(conn_ids), lengths, blob
        )
        with self._wlock:
            wire.send_msg(self.sock, wire.MSG_DATA_BATCH, payload)
