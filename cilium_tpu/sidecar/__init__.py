"""Sidecar verdict-service seam — the native ingestion boundary.

The reference's L7 hot path crosses a process/language seam between the
Envoy datapath and the verdict library: ``GoFilter::Instance::OnIO``
(reference: envoy/cilium_proxylib.cc:125-214) calls the cgo exports in
``proxylib/libcilium.h`` and applies the returned ``FilterOp`` list
(PASS/DROP/INJECT/MORE, max 16 ops per call) to its byte buffers.

This package is the TPU-native equivalent of that seam:

- ``wire``     — a columnar binary protocol for per-connection byte batches
                 and FilterOp verdict batches over a unix socket (the ABI
                 analog of libcilium.h, shaped for numpy/device dispatch)
- ``dispatch`` — the adaptive fill-vs-deadline batch dispatcher (consumes
                 ``batch_timeout_ms``; bounds added latency while filling
                 device batches)
- ``service``  — the verdict service: module/policy registry + batched
                 device models behind the wire protocol
- ``client``   — a Python datapath shim (per-connection buffering, the
                 OnIO byte-accounting contract) used by tests and benches
- ``trace``    — verdict-path latency decomposition: per-round stage
                 histograms, sampled spans, slow-verdict exemplars
- ``shm``      — lock-free SPSC shared-memory rings (the zero-copy data
                 fast path between shim and service)
- ``transport``— the transport seam: socket control channel + shm data
                 rung, fallback reasons, per-session telemetry

The native C++ shim implementing the same client contract lives in
``native/`` (built to ``libcilium_tpu_shim.so``).
"""

from .client import ShimConnection, SidecarClient, SidecarUnavailable
from .dispatch import BatchDispatcher
from .guard import DeviceGuard, DeviceStall
from .service import VerdictService
from .shm import RingError, ShmRing, TornSlot
from .trace import RoundTrace, VerdictTracer
from .transport import TRANSPORT_SHM, TRANSPORT_SOCKET, ShmPeer, ShmSession

__all__ = [
    "BatchDispatcher",
    "DeviceGuard",
    "DeviceStall",
    "RingError",
    "RoundTrace",
    "ShimConnection",
    "ShmPeer",
    "ShmRing",
    "ShmSession",
    "SidecarClient",
    "SidecarUnavailable",
    "TornSlot",
    "TRANSPORT_SHM",
    "TRANSPORT_SOCKET",
    "VerdictService",
    "VerdictTracer",
]
