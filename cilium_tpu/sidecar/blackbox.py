"""Flight recorder: the declared-edge incident timeline.

PR 18 declared every lifecycle invariant as data (`analysis/protocols.py`
typestate tables) and routed every transition through ONE choke point —
``Typestate.advance``/``guard``/``require_edges``.  This module hooks
that choke point so an operator reconstructing a degradation cascade
gets an ORDERED, CORRELATED record of which declared edges fired, in
what sequence, with what reasons — instead of scattered counters:

- **Timeline ring.**  Every mediated transition lands in a bounded
  ``deque(maxlen=timeline_ring)`` as ``(monotonic seq, wall time,
  table, edge, outcome)`` plus whatever correlation ids the transition
  site annotated (session / conn / epoch / round / device / reason).
  Appends are GIL-atomic; transitions are control-plane events (session
  containment, policy swaps, mesh rungs, cache arm/disarm), never the
  per-entry verdict loop, so the always-on cost is the ``is None``
  observer test in ``protocols.py`` — nothing else (BENCH_NOTES
  ``timeline_overhead``).
- **Overload markers.**  Shed bursts, DRR window clips and dispatch
  stalls are coalesced per kind into one ring event per 0.25s window
  (the event's ``n`` keeps accumulating in place), so a 50k-entry shed
  storm costs one dict mutation per entry-batch, not 50k ring events.
- **Occupancy series.**  ``sample_round`` (called once per dispatch
  round from ``VerdictTracer.finish_round``) folds device-busy
  seconds, batch occupancy, queue depth and admission headroom into
  1-second buckets — the time-series ROADMAP item 4's occupancy-aware
  tier switch consumes.
- **Postmortem bundles.**  Any edge in ``protocols.FAIL_CLOSED``
  (quarantine, mesh descent, shm demotion, session death, swap
  failure, kvstore degraded) snapshots the ring SYNCHRONOUSLY (the
  triggering edge is the snapshot's last event) and hands enrichment —
  stage-latency snapshot, relevant ``status()`` sections, JSON file
  write, monitor fan-out — to a daemon thread.  The enrichment MUST
  be asynchronous: fail-closed advances fire under ``service._lock``
  and a synchronous ``status()`` call would self-deadlock.  A global
  armed-latch (re-armed when any fail-closed table returns to its
  initial state, i.e. on heal) plus a time floor keeps it to one
  bundle per descent, not one per edge of the cascade.

Multiple services can coexist in one process (the hitless-handoff
tests run old+new side by side), so recorders register in a module
tuple and the single ``protocols`` observer fans out to all of them.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

from ..analysis import protocols
from ..utils import metrics

# One ring event per overload kind per this many seconds — the window
# an in-place ``n`` accumulates over.
OVERLOAD_WINDOW_S = 0.25

# Occupancy bucket width (matches VerdictTracer.BUSY_WINDOW_S).
BUCKET_S = 1.0

# Minimum spacing between postmortem bundles while the latch is down
# (a heal re-arms immediately; this floor only bounds a cascade that
# never heals).
FAIL_CLOSED_DEBOUNCE_S = 10.0

# ``(table, to)`` pairs that mean the subsystem returned to its
# protocol's INITIAL (healthy) state — these re-arm the postmortem
# latch, so the NEXT descent gets its own bundle.
_REARM_EDGES = frozenset({
    ("session", protocols.SESSION_ACTIVE),
    ("device_guard", protocols.GUARD_SERVING),
    ("mesh_device", protocols.DEVICE_OK),
    ("mesh_ladder", protocols.MESH_FULL),
    ("epoch_swap", protocols.SWAP_COMMITTED),
})

# Marker tokens that signal recovery rather than failure (they re-arm
# the latch and reset the transport tier instead of triggering).
_REARM_MARKS = frozenset({"shm_attach", "kvstore_restored"})

# ``(table, to)`` -> (subsystem, tier) for the unified serving-tier
# gauge: 0 is the full-speed rung, higher is narrower.  Transport tier
# moves via marks (shm_demotion / shm_attach) — it has no typestate.
_TIER_EDGES = {
    ("mesh_ladder", protocols.MESH_FULL): ("mesh", 0),
    ("mesh_ladder", protocols.MESH_RESHAPED): ("mesh", 1),
    ("mesh_ladder", protocols.MESH_FALLBACK): ("mesh", 2),
    ("device_guard", protocols.GUARD_SERVING): ("guard", 0),
    ("device_guard", protocols.GUARD_QUARANTINED): ("guard", 1),
    ("flow_cache", protocols.CACHE_ARMED): ("cache", 0),
    ("flow_cache", protocols.CACHE_UNARMED): ("cache", 1),
}

SUBSYSTEMS = ("mesh", "guard", "cache", "transport")


# -- transition-site annotations (thread-local) ---------------------------
#
# A transition site knows WHY it is advancing (reason string) and WHO
# it is advancing for (session / conn / epoch / device ids); the
# protocols observer only sees (table, frm, to, outcome).  Sites wrap
# the advance in ``with blackbox.annotate(reason=..., session=...)``
# and the recorder folds the stack into the event.  Thread-local, so
# concurrent handler threads never cross-label each other's edges.

_ANNOT = threading.local()


class annotate:
    """Context manager attaching correlation ids to every transition
    recorded on this thread while the block is live.  Nestable; inner
    keys win."""

    __slots__ = ("ids",)

    def __init__(self, **ids):
        self.ids = ids

    def __enter__(self):
        stack = getattr(_ANNOT, "stack", None)
        if stack is None:
            stack = _ANNOT.stack = []
        stack.append(self.ids)
        return self

    def __exit__(self, *exc):
        _ANNOT.stack.pop()
        return False


def _annotations() -> dict | None:
    stack = getattr(_ANNOT, "stack", None)
    if not stack:
        return None
    if len(stack) == 1:
        return stack[0]
    merged: dict = {}
    for d in stack:
        merged.update(d)
    return merged


# -- process-wide registry ------------------------------------------------

_REG_LOCK = threading.Lock()
_RECORDERS: tuple = ()


def _dispatch(table, frm, to, outcome) -> None:
    """The ONE callback installed as the protocols transition observer
    (containment lives in ``protocols._observe``)."""
    for rec in _RECORDERS:
        rec._on_transition(table, frm, to, outcome)


def broadcast_mark(token: str, **ids) -> None:
    """Record a non-typestate marker on every installed recorder — the
    entry point for code that has no service handle (the daemon's
    kvstore-degraded latch).  No-op when nothing is installed."""
    for rec in _RECORDERS:
        try:
            rec.record_mark(token, **ids)
        except Exception:  # noqa: BLE001 -- a marker must never fail its caller
            pass


class FlightRecorder:
    """Always-on, bounded, lock-light incident recorder for one
    service (see module docstring for the design contract)."""

    def __init__(self, *, ring: int = 512, bundle_dir: str = "",
                 slow_only: bool = False):
        self.ring: deque = deque(maxlen=max(int(ring), 1))
        self.bundle_dir = bundle_dir or ""
        self.slow_only = bool(slow_only)
        self._seq = itertools.count(1)
        self.debounce_s = FAIL_CLOSED_DEBOUNCE_S
        # Enrichment providers, attached externally by the service
        # (same pattern as VerdictTracer.monitor/access_logger).
        self.monitor = None           # monitor.Monitor (notify())
        self.stage_provider = None    # () -> per-path stage snapshot
        self.status_provider = None   # () -> relevant status() sections
        self.occupancy_probe = None   # () -> (queue_depth, headroom)
        # Postmortem latch (one bundle per descent).
        self._plock = threading.Lock()
        self._armed = True
        self._last_bundle_mono = -1e9
        self.postmortems: deque = deque(maxlen=8)
        self.bundles_written = 0
        self.bundles_suppressed = 0
        self.fail_closed_events = 0
        # Overload coalescing: kind -> (window_start_mono, ring event).
        self._over: dict = {}
        # Occupancy buckets: closed buckets ride a deque; the open
        # bucket is mutated under a short per-round lock.
        self._olock = threading.Lock()
        self._obuckets: deque = deque(maxlen=64)
        self._ocur: dict | None = None
        # Unified serving-tier gauge state (last value per subsystem).
        self._tiers: dict = {}

    # -- install / uninstall ----------------------------------------------

    def install(self) -> "FlightRecorder":
        """Register with the process-wide observer and zero the
        serving-tier gauge for every subsystem (a scrape before the
        first transition must show the full-speed rung)."""
        global _RECORDERS
        with _REG_LOCK:
            if self not in _RECORDERS:
                _RECORDERS = _RECORDERS + (self,)
            protocols.set_transition_observer(_dispatch)
        for sub in SUBSYSTEMS:
            self._set_tier(sub, 0)
        return self

    def uninstall(self) -> None:
        global _RECORDERS
        with _REG_LOCK:
            _RECORDERS = tuple(r for r in _RECORDERS if r is not self)
            if not _RECORDERS:
                protocols.set_transition_observer(None)

    # -- the transition sink ----------------------------------------------

    def _on_transition(self, table, frm, to, outcome) -> None:
        fail = (table, frm, to) in protocols.FAIL_CLOSED_EDGES
        ev = None
        if fail or not (self.slow_only and outcome is None):
            ev = {"seq": next(self._seq), "t": time.time(),
                  "table": table, "edge": [frm, to], "outcome": outcome}
            ann = _annotations()
            if ann:
                ev.update(ann)
            if fail:
                ev["fail_closed"] = True
            self.ring.append(ev)
        tier = _TIER_EDGES.get((table, to))
        if tier is not None:
            self._set_tier(tier[0], tier[1])
        if fail:
            self.fail_closed_events += 1
            self._fail_closed(ev)
        elif (table, to) in _REARM_EDGES:
            self._rearm()

    # -- markers / overload -----------------------------------------------

    def record_mark(self, token: str, **ids) -> None:
        """A non-typestate lifecycle marker (shm transport demotion,
        kvstore degradation, and their recovery twins)."""
        ev = {"seq": next(self._seq), "t": time.time(), "table": "mark",
              "edge": ["-", token], "outcome": None}
        if ids:
            ev.update(ids)
        fail = token in protocols.FAIL_CLOSED_MARKERS
        if fail:
            ev["fail_closed"] = True
        self.ring.append(ev)
        if token == "shm_demotion":
            self._set_tier("transport", 1)
        elif token == "shm_attach":
            self._set_tier("transport", 0)
        if fail:
            self.fail_closed_events += 1
            self._fail_closed(ev)
        elif token in _REARM_MARKS:
            self._rearm()

    def record_overload(self, kind: str, n: int = 1) -> None:
        """Coalesced overload marker (shed burst, DRR window clip,
        queue high-water, dispatch stall): ONE ring event per kind per
        window; its ``n`` accumulates in place."""
        now = time.monotonic()
        cur = self._over.get(kind)
        if cur is not None and now - cur[0] < OVERLOAD_WINDOW_S:
            cur[1]["n"] += n
            return
        ev = {"seq": next(self._seq), "t": time.time(),
              "table": "overload", "edge": ["-", kind],
              "outcome": None, "n": n}
        self._over[kind] = (now, ev)
        self.ring.append(ev)

    # -- occupancy series -------------------------------------------------

    def sample_round(self, n: int, capacity: int, device_s: float,
                     now: float | None = None) -> None:
        """Fold one dispatch round into the open occupancy bucket.
        Called from ``VerdictTracer.finish_round`` — once per ROUND,
        never per entry (the same cadence contract as the tracer's own
        accumulators)."""
        if now is None:
            now = time.monotonic()
        queue = headroom = None
        probe = self.occupancy_probe
        if probe is not None:
            try:
                queue, headroom = probe()
            except Exception:  # noqa: BLE001 -- probe faults must not cost the round
                pass
        with self._olock:
            b = self._ocur
            if b is None or now - b["t0"] >= BUCKET_S:
                if b is not None:
                    self._obuckets.append(self._close_bucket(b))
                b = self._ocur = {
                    "t0": now, "t": time.time(), "rounds": 0,
                    "items": 0, "cap": 0, "device_s": 0.0,
                    "queue_max": 0, "headroom_min": None,
                }
            b["rounds"] += 1
            b["items"] += int(n)
            b["cap"] += max(int(capacity), 1)
            b["device_s"] += float(device_s)
            if queue is not None and queue > b["queue_max"]:
                b["queue_max"] = queue
            if headroom is not None and (b["headroom_min"] is None
                                         or headroom < b["headroom_min"]):
                b["headroom_min"] = headroom

    @staticmethod
    def _close_bucket(b: dict) -> dict:
        return {
            "t": round(b["t"], 3),
            "rounds": b["rounds"],
            "items": b["items"],
            "busy": round(min(b["device_s"] / BUCKET_S, 1.0), 4),
            "occupancy": round(b["items"] / b["cap"], 4) if b["cap"] else 0.0,
            "queue_max": b["queue_max"],
            "headroom_min": b["headroom_min"],
        }

    # -- serving-tier gauge -----------------------------------------------

    def _set_tier(self, subsystem: str, tier: int) -> None:
        if self._tiers.get(subsystem) == tier:
            return
        self._tiers[subsystem] = tier
        metrics.ServingTier.set(tier, subsystem)

    # -- postmortem latch -------------------------------------------------

    def _rearm(self) -> None:
        self._armed = True

    def _fail_closed(self, ev: dict) -> None:
        now = time.monotonic()
        with self._plock:
            if (not self._armed
                    and now - self._last_bundle_mono < self.debounce_s):
                self.bundles_suppressed += 1
                return
            self._armed = False
            self._last_bundle_mono = now
            # Snapshot NOW, under the latch: the triggering edge is the
            # ring's newest entry, so it lands LAST in the bundle and a
            # racing cascade edge cannot leak in ahead of the write.
            events = list(self.ring)
        trigger = f"{ev['table']}:{ev['edge'][0]}->{ev['edge'][1]}"
        t = threading.Thread(
            target=self._build_bundle, args=(trigger, ev, events),
            name="blackbox-postmortem", daemon=True,
        )
        t.start()

    def _build_bundle(self, trigger: str, ev: dict, events: list) -> None:
        """Enrich + persist + fan out one postmortem bundle.  Runs on
        its own daemon thread: fail-closed edges fire under service
        locks, and the status/stage providers take those same locks —
        a synchronous call here would self-deadlock.  Every sink is
        contained; a broken provider still yields a bundle."""
        bundle = {
            "trigger": trigger,
            "seq": ev.get("seq"),
            "t": ev.get("t"),
            "reason": ev.get("reason"),
            "events": events,
        }
        stage = self.stage_provider
        if stage is not None:
            try:
                bundle["stages"] = stage()
            except Exception:  # noqa: BLE001 -- enrichment is best-effort
                bundle["stages"] = None
        status = self.status_provider
        if status is not None:
            try:
                bundle["status"] = status()
            except Exception:  # noqa: BLE001 -- enrichment is best-effort
                bundle["status"] = None
        path = None
        if self.bundle_dir:
            try:
                os.makedirs(self.bundle_dir, exist_ok=True)
                fname = "postmortem_%06d_%s.json" % (
                    ev.get("seq") or 0,
                    "".join(c if c.isalnum() else "_" for c in trigger),
                )
                path = os.path.join(self.bundle_dir, fname)
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(bundle, f, indent=1, default=str)
                os.replace(tmp, path)
            except OSError:
                path = None
        summary = {
            "trigger": trigger,
            "seq": ev.get("seq"),
            "t": ev.get("t"),
            "reason": ev.get("reason"),
            "events": len(events),
            "path": path,
        }
        self.postmortems.append(summary)
        self.bundles_written += 1
        metrics.SidecarPostmortems.inc(ev.get("table", "mark"))
        mon = self.monitor
        if mon is not None:
            try:
                from ..monitor.monitor import (
                    MSG_TYPE_POSTMORTEM,
                    MonitorEvent,
                )

                mon.notify(MonitorEvent(MSG_TYPE_POSTMORTEM, summary))
            except Exception:  # noqa: BLE001 — sink must not poison path
                pass

    # -- read side ---------------------------------------------------------

    def events(self, n: int = 100, since: int = 0,
               table: str | None = None) -> list[dict]:
        """Oldest-first snapshot of the timeline, filtered by minimum
        seq and/or table — the MSG_TIMELINE read path."""
        out = [e for e in list(self.ring)
               if e["seq"] > since
               and (table is None or e["table"] == table)]
        return out[-max(int(n), 0):]

    def occupancy(self) -> list[dict]:
        """Closed occupancy buckets, oldest first, plus the open one."""
        with self._olock:
            out = list(self._obuckets)
            if self._ocur is not None:
                out.append(self._close_bucket(self._ocur))
        return out

    def status(self) -> dict:
        try:
            last_seq = self.ring[-1]["seq"]
        except IndexError:
            last_seq = 0
        last_pm = None
        try:
            last_pm = self.postmortems[-1]
        except IndexError:
            pass
        return {
            "events": len(self.ring),
            "ring": self.ring.maxlen,
            "seq": last_seq,
            "fail_closed_events": self.fail_closed_events,
            "postmortems": self.bundles_written,
            "postmortems_suppressed": self.bundles_suppressed,
            "last_postmortem": last_pm,
            "armed": self._armed,
            "tiers": dict(self._tiers),
            "slow_only": self.slow_only,
        }

    def dump(self, n: int = 100, since: int = 0,
             table: str | None = None) -> dict:
        """The full MSG_TIMELINE_REPLY payload."""
        return {
            "events": self.events(n=n, since=since, table=table),
            "occupancy": self.occupancy(),
            "postmortems": list(self.postmortems),
            "timeline": self.status(),
        }
