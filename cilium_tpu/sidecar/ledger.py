"""Device-economics ledger: compile accounting + batch-formation provenance.

The flight recorder (PR 19) answers *what incident happened*; the
stage traces (PR 4) answer *where a round spent its time*.  This
module answers the two remaining economic questions that gate ROADMAP
items 4 and 5:

- **Why did a compile happen?**  Every executable-producing site —
  the service's shape-keyed jit caches (``_jit_for`` /
  ``_model_call`` / ``_model_call_attr`` / ``_gathered_call``),
  prewarm, the policy-builder's swap/rebind/mesh-reshape/re-promotion
  rebuilds, and the daemon-side engine builders — routes through ONE
  choke point, :meth:`DeviceLedger.record_compile`, which stamps the
  event with a **cause** from a closed taxonomy (:data:`CAUSES`), the
  shape signature, rule bucket, mesh layout, engine family, wall
  seconds, epoch, and an on-dispatch-path flag.  Events land in a
  bounded ring plus ``device_compiles_total{cause,family}`` /
  ``device_compile_seconds`` histograms and an executables-resident
  gauge.  Two folklore claims become *asserted invariants*: warm
  churn performs ZERO compiles (the churn soak asserts the churn-*
  cause counters stay flat across a warm window) and no compile ever
  lands on the dispatch path (``dispatch_path_compiles`` stays 0).

- **Why was a batch issued?**  Every dispatch round is stamped with
  its formation **trigger** (:data:`TRIGGERS`), occupancy fraction,
  queue depth, oldest-entry age at pop, and bytes at issue — one
  stamp per ROUND, never per entry, riding the existing
  ``VerdictTracer.finish_round`` cadence next to the blackbox
  occupancy sample.  Per-trigger µs-bucket histograms plus a small
  per-trigger accumulator make item 4's tier-switching policy
  decidable from recorded data.

Causes are communicated to the choke point through a thread-local
scope stack (:class:`cause_scope`), mirroring ``blackbox.annotate``:
the policy builder wraps a swap rebuild in
``with ledger.cause_scope("churn-new-shape", epoch=...)`` and every
compile recorded on that thread inside the block inherits the cause.
A compile recorded with no scope and no explicit cause is ``cold`` —
the safe default that makes an unlabeled site visible rather than
silently miscounted.  The dispatch-path flag needs no site
cooperation: the dispatcher already brands its worker thread with
``_disp_round`` for the round's lifetime, so the ledger reads it.

Multiple services coexist in one process (hitless-handoff tests), so
ledgers register in a module tuple like the flight recorders;
:func:`broadcast_compile` is the entry point for code with no service
handle (the daemon-side engine builder).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from ..utils import metrics

# Closed cause taxonomy — every recorded compile carries exactly one.
CAUSE_COLD = "cold"                      # first build of an engine/shape
CAUSE_PREWARM = "prewarm"                # off-path warm at build/swap
CAUSE_CHURN_NEW_SHAPE = "churn-new-shape"  # policy churn grew a new bucket
CAUSE_CHURN_VOCAB = "churn-vocab"        # same bucket, new automaton vocab
CAUSE_MESH_RESHAPE = "mesh-reshape"      # degraded-mesh rebuild
CAUSE_REPROMOTION = "repromotion"        # heal walking back up the ladder
CAUSE_HEAL_REBIND = "heal-rebind"        # kvstore/daemon rebind rebuild

CAUSES = (
    CAUSE_COLD, CAUSE_PREWARM, CAUSE_CHURN_NEW_SHAPE, CAUSE_CHURN_VOCAB,
    CAUSE_MESH_RESHAPE, CAUSE_REPROMOTION, CAUSE_HEAL_REBIND,
)

# Closed formation-trigger taxonomy — every dispatch round carries
# exactly one (the dispatcher classifies at pop).
TRIGGER_SIZE_FULL = "size-full"      # pending weight reached max_batch
TRIGGER_FLUSH = "flush"              # stop()/drain pop
TRIGGER_DEADLINE = "deadline"        # batch window expired with a partial
TRIGGER_IDLE_GREEDY = "idle-greedy"  # timeout<=0 greedy issue on idle
TRIGGER_CUT_THROUGH = "cut-through"  # inline round, queue bypassed

TRIGGERS = (
    TRIGGER_SIZE_FULL, TRIGGER_FLUSH, TRIGGER_DEADLINE,
    TRIGGER_IDLE_GREEDY, TRIGGER_CUT_THROUGH,
)

# The churn-cause subset the "warm churn performs zero compiles"
# invariant is asserted over.
CHURN_CAUSES = frozenset({CAUSE_CHURN_NEW_SHAPE, CAUSE_CHURN_VOCAB})


# -- thread-local cause scopes --------------------------------------------

_SCOPE = threading.local()


class cause_scope:
    """Attach a compile cause (plus correlation ids) to every compile
    recorded on this thread while the block is live.  Nestable; the
    innermost scope wins — a mesh-reshape rebuild that calls the
    common prewarm helper still records ``prewarm`` for the inner
    warms only if the helper opens its own scope."""

    __slots__ = ("ids",)

    def __init__(self, cause: str, **ids):
        self.ids = dict(ids)
        self.ids["cause"] = cause

    def __enter__(self):
        stack = getattr(_SCOPE, "stack", None)
        if stack is None:
            stack = _SCOPE.stack = []
        stack.append(self.ids)
        return self

    def __exit__(self, *exc):
        _SCOPE.stack.pop()
        return False


def current_scope() -> dict | None:
    stack = getattr(_SCOPE, "stack", None)
    if not stack:
        return None
    if len(stack) == 1:
        return stack[0]
    merged: dict = {}
    for d in stack:
        merged.update(d)
    return merged


def _on_dispatch_path() -> bool:
    """True when the calling thread is inside a dispatch round — the
    dispatcher brands its worker thread with ``_disp_round`` for the
    round's lifetime (and the cut-through path brands the caller
    thread the same way), so no site cooperation is needed."""
    return getattr(threading.current_thread(), "_disp_round", None) is not None


# -- process-wide registry ------------------------------------------------

_REG_LOCK = threading.Lock()
_LEDGERS: tuple = ()


def broadcast_compile(family: str, seconds: float, **fields) -> None:
    """Record a compile on every installed ledger — the entry point
    for code with no service handle (the daemon-side engine builder).
    No-op when nothing is installed."""
    for led in _LEDGERS:
        try:
            led.record_compile(family, seconds, **fields)
        except Exception:  # noqa: BLE001 -- accounting must never fail its caller
            pass


class DeviceLedger:
    """Always-on, bounded, lock-light compile/formation ledger for one
    service (see module docstring for the design contract)."""

    def __init__(self, *, ring: int = 256):
        self.ring: deque = deque(maxlen=max(int(ring), 1))
        self._seq = itertools.count(1)
        # Compile-side totals.  Mutated under _clock: compiles are
        # control-plane rate (builder threads, never per entry), so a
        # short lock keeps cross-thread counts exact for the asserted
        # invariants.
        self._clock = threading.Lock()
        self.compiles_total = 0
        self.compile_seconds = 0.0
        self.by_cause: dict = {c: 0 for c in CAUSES}
        self.dispatch_path_compiles = 0
        # One definition of "executable resident": shape keys counted
        # in on first cache insert, counted out by SHAPE_CACHE_MAX
        # eviction and epoch retirement.  The set (not a bare int)
        # also answers "is this shape already resident" — the signal
        # that splits churn-new-shape from churn-vocab.
        self._resident: set = set()
        # Previously-resident keys (bounded, insertion-ordered): the
        # evict-then-reuse signal — a re-trace of a key found here is
        # churn cost (churn-new-shape), not a cold start.
        self._evicted: dict = {}
        # Formation side: per-trigger accumulators, one short lock
        # trip per ROUND (same cadence contract as the blackbox
        # occupancy sample — never per entry).
        self._flock = threading.Lock()
        self._formation: dict = {}
        self.rounds_total = 0

    # -- install / uninstall ----------------------------------------------

    def install(self) -> "DeviceLedger":
        global _LEDGERS
        with _REG_LOCK:
            if self not in _LEDGERS:
                _LEDGERS = _LEDGERS + (self,)
        return self

    def uninstall(self) -> None:
        global _LEDGERS
        with _REG_LOCK:
            _LEDGERS = tuple(x for x in _LEDGERS if x is not self)

    # -- compile ledger (the choke point) ----------------------------------

    def record_compile(self, family: str, seconds: float, *,
                       cause: str | None = None, shape=None, rules=None,
                       mesh=None, epoch=None, **ids) -> dict:
        """THE executable-producing choke point.  Every jit trace,
        automaton compile, or engine build in the serving tree calls
        this exactly once per produced executable (lint R23 proves
        it).  Cause resolution: explicit argument, else the innermost
        thread-local :class:`cause_scope`, else ``cold``."""
        scope = current_scope()
        if cause is None:
            cause = (scope or {}).get("cause", CAUSE_COLD)
        on_path = _on_dispatch_path()
        ev = {
            "seq": next(self._seq),
            "t": time.time(),
            "cause": cause,
            "family": str(family),
            "seconds": round(float(seconds), 6),
            "on_dispatch_path": on_path,
        }
        if shape is not None:
            ev["shape"] = self._sig(shape)
        if rules is not None:
            ev["rules"] = rules
        if mesh is not None:
            ev["mesh"] = mesh
        if scope:
            for k, v in scope.items():
                if k != "cause":
                    ev.setdefault(k, v)
        if epoch is not None:
            ev["epoch"] = epoch
        if ids:
            ev.update(ids)
        with self._clock:
            self.compiles_total += 1
            self.compile_seconds += float(seconds)
            self.by_cause[cause] = self.by_cause.get(cause, 0) + 1
            if on_path:
                self.dispatch_path_compiles += 1
            self.ring.append(ev)
        metrics.DeviceCompilesTotal.inc(cause, str(family))
        metrics.DeviceCompileSeconds.observe(float(seconds), cause)
        return ev

    @staticmethod
    def _sig(shape) -> str:
        """Stable, JSON-safe rendering of a shape key/signature."""
        try:
            return repr(shape)
        except Exception:  # noqa: BLE001 -- a weird key must not fail the record
            return "<unrenderable>"

    # -- resident-executables gauge ----------------------------------------

    def executable_resident(self, key) -> bool:
        """Count a shape-keyed executable in.  Returns True when the
        key was ALREADY resident — the evict-then-reuse signal that
        makes a re-trace ``churn-new-shape``/``churn-vocab`` rather
        than ``cold`` in the caller's bookkeeping."""
        with self._clock:
            known = key in self._resident
            self._resident.add(key)
            self._evicted.pop(key, None)
            n = len(self._resident)
        metrics.ExecutablesResident.set(n)
        return known

    def executable_evicted(self, key) -> None:
        """Count a shape-keyed executable out (SHAPE_CACHE_MAX
        eviction, epoch retirement) — the single decrement site the
        prewarm bookkeeping dedupes against."""
        with self._clock:
            if key in self._resident:
                self._resident.discard(key)
                self._evicted[key] = True
                while len(self._evicted) > 1024:
                    self._evicted.pop(next(iter(self._evicted)))
            n = len(self._resident)
        metrics.ExecutablesResident.set(n)

    def is_resident(self, key) -> bool:
        with self._clock:
            return key in self._resident

    def was_evicted(self, key) -> bool:
        with self._clock:
            return key in self._evicted

    @property
    def executables_resident(self) -> int:
        with self._clock:
            return len(self._resident)

    # -- batch-formation provenance ----------------------------------------

    def stamp_round(self, trigger: str, n: int, capacity: int,
                    depth: int = 0, age_s: float = 0.0,
                    bytes_at_issue: int = 0) -> None:
        """Fold one dispatch round's formation stamp into the
        per-trigger accumulator.  Called from
        ``VerdictTracer.finish_round`` — once per ROUND, never per
        entry."""
        cap = max(int(capacity), 1)
        occ = min(int(n) / cap, 1.0)
        with self._flock:
            self.rounds_total += 1
            acc = self._formation.get(trigger)
            if acc is None:
                acc = self._formation[trigger] = {
                    "rounds": 0, "items": 0, "occ_sum": 0.0,
                    "age_sum": 0.0, "age_max": 0.0,
                    "depth_max": 0, "bytes": 0,
                }
            acc["rounds"] += 1
            acc["items"] += int(n)
            acc["occ_sum"] += occ
            acc["age_sum"] += float(age_s)
            if age_s > acc["age_max"]:
                acc["age_max"] = float(age_s)
            if depth > acc["depth_max"]:
                acc["depth_max"] = int(depth)
            acc["bytes"] += int(bytes_at_issue)
        metrics.BatchFormationRounds.inc(trigger)
        metrics.BatchFormationAge.observe(max(float(age_s), 0.0), trigger)

    # -- read side ---------------------------------------------------------

    def events(self, n: int = 100, since: int = 0,
               cause: str | None = None) -> list[dict]:
        """Oldest-first snapshot of the compile ring, filtered by
        minimum seq and/or cause — the MSG_LEDGER read path."""
        with self._clock:
            snap = list(self.ring)
        out = [e for e in snap
               if e["seq"] > since
               and (cause is None or e["cause"] == cause)]
        return out[-max(int(n), 0):]

    def formation(self) -> dict:
        """Per-trigger formation summary with derived means."""
        with self._flock:
            snap = {k: dict(v) for k, v in self._formation.items()}
        for acc in snap.values():
            r = acc["rounds"] or 1
            acc["occ_mean"] = round(acc.pop("occ_sum") / r, 4)
            acc["age_mean_s"] = round(acc.pop("age_sum") / r, 6)
            acc["age_max_s"] = round(acc.pop("age_max"), 6)
        return snap

    def status(self) -> dict:
        with self._clock:
            last_seq = self.ring[-1]["seq"] if self.ring else 0
            by_cause = {c: n for c, n in self.by_cause.items() if n}
            return {
                "compiles": self.compiles_total,
                "compile_seconds": round(self.compile_seconds, 6),
                "by_cause": by_cause,
                "churn_compiles": sum(
                    n for c, n in self.by_cause.items()
                    if c in CHURN_CAUSES),
                "dispatch_path_compiles": self.dispatch_path_compiles,
                "executables_resident": len(self._resident),
                "rounds": self.rounds_total,
                "seq": last_seq,
                "ring": self.ring.maxlen,
            }

    def dump(self, n: int = 100, since: int = 0,
             cause: str | None = None) -> dict:
        """The full MSG_LEDGER_REPLY payload."""
        return {
            "compiles": self.events(n=n, since=since, cause=cause),
            "formation": self.formation(),
            "ledger": self.status(),
        }
