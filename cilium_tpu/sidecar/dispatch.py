"""Adaptive fill-vs-deadline batch dispatcher with fail-closed overload
and stall containment.

Device dispatch is most efficient at full batches, but a request that
arrives into an idle service must not wait a full batch's worth of fill
time — the p99 budget is <1ms added latency (BASELINE.json north star).
The dispatcher implements the standard fill-vs-deadline tradeoff:

- a batch is dispatched immediately once pending work reaches
  ``max_batch`` entries (fill), or
- when the oldest pending entry has waited ``batch_timeout_ms``
  (deadline), whichever comes first.

``batch_timeout_ms=0`` selects GREEDY mode: the worker takes whatever
is pending the moment it frees up.  While a round is being processed,
arrivals coalesce naturally into the next round (self-adaptive batching
— steady-state round size ≈ arrival rate × round service time), and an
idle service adds zero queue wait.  This is the right mode when the
per-round device cost is small and local (co-located chip); the
deadline mode wins when each round pays a large fixed transport cost
worth amortizing across more entries.

The deadline timer arms when the first item lands in an empty queue, so
an idle service adds at most ``batch_timeout_ms`` + one device pass to
any request.  This is the consumer of ``DaemonConfig.batch_timeout_ms``
(utils/option.py) — the reference has no device batching; its nearest
analog is the per-request proxy dispatch in GoFilter::Instance::OnIO
(reference: envoy/cilium_proxylib.cc:125), which this component amortizes
across flows.

Containment contract (the robustness layer):

- **Bounded admission**: ``max_pending`` caps queued weight; ``submit``
  refuses excess work (returns False) so the caller can answer with a
  typed SHED verdict instead of queueing unboundedly.  ``force=True``
  bypasses the cap for control items (closes) that must never be lost.
- **Crash containment**: a ``process(batch)`` that raises reaches
  ``on_batch_error(batch, exc)`` so every in-flight entry can receive a
  typed error verdict — never logged-and-dropped.
- **Stall containment**: an optional watchdog bounds one round at
  ``stall_timeout_s``.  A worker stuck past the deadline (device hang)
  is DEPOSED: the stuck batch goes to ``on_stall(batch)`` for typed
  shed verdicts, a replacement worker takes over the queue, and the
  stuck ROUND's late sends — from the abandoned thread or from
  completion-pipeline records it already queued — are suppressed per
  round (consumers check ``thread_is_deposed()`` /
  ``thread_round_is_shed()``; per-generation suppression alone would
  also swallow earlier completed rounds still in the pipeline).
  Python cannot cancel the stuck thread; it is abandoned (daemon) and
  exits when the stall clears.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

from ..utils import metrics

log = logging.getLogger(__name__)


class BatchDispatcher:
    """Collects submitted items and hands batches to ``process`` on a
    dedicated worker thread.

    ``process(items)`` receives the pending list (oldest first).  Each
    item carries a ``weight`` (entry count for wire requests) counted
    toward the fill threshold and the admission cap.
    """

    def __init__(
        self,
        process: Callable[[list[Any]], None],
        max_batch: int = 2048,
        timeout_ms: float = 0.5,
        name: str = "verdict-dispatch",
        max_pending: int = 0,
        stall_timeout_s: float = 0.0,
        on_batch_error: Callable[[list[Any], BaseException], None] | None = None,
        on_stall: Callable[[list[Any]], None] | None = None,
    ):
        self.process = process
        self.max_batch = max_batch
        self.timeout_s = timeout_ms / 1000.0
        self.max_pending = max_pending
        self.stall_timeout_s = stall_timeout_s
        self.on_batch_error = on_batch_error
        self.on_stall = on_stall
        self._name = name
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # Signalled every time a round finishes (flush waits here —
        # never a sleep/poll loop).
        self._done = threading.Condition(self._lock)
        self._pending: list[Any] = []
        self._pending_weight = 0
        # Byte-weighted outstanding work (PR 15 remainder): payload
        # bytes admitted and not yet popped.  Charged beside weight at
        # submit, drained wholesale at pop; the drr_outstanding_bytes
        # gauge is sampled once per ROUND at pop (bytes at issue), so
        # the per-entry admission path never touches the registry.
        self._pending_bytes = 0
        # Per-session queued weight (fan-in DRR): sessions passed to
        # submit/submit_many get their q_weight bumped under _cond and
        # zeroed WHOLESALE at every pop — the pop takes the entire
        # pending list, so every session's unused share replenishes at
        # once, paced by service progress (deficit round robin over
        # queue slots).  The set holds only sessions with weight in
        # the CURRENT queue generation.
        self._q_sessions: set[Any] = set()
        self._oldest_ts = 0.0
        self._stopped = False
        self._started = False
        self._in_process_lock = threading.Lock()
        # True from the moment the worker pops a batch in _take until it
        # finishes processing it.  Set BEFORE _pending is cleared (both
        # under _cond) so a lock-free reader that observes an empty
        # _pending is guaranteed to observe _busy=True for any popped
        # batch still in flight — the ordering the service's cut-through
        # path relies on to never overtake queued work.
        self._busy = False
        # Worker generation: bumped at each stall deposal.  The current
        # worker and the current in-process lock are keyed to it.
        self._gen = 0
        self._round_start = 0.0
        self._current_batch: list[Any] | None = None
        # Round ids: every dispatch round (worker pop OR cut-through
        # inline round) gets a unique id, recorded on the processing
        # thread as ``_disp_round``.  round_seq only advances while
        # _busy is false (pop and inline begin both require it), so
        # while a round is in flight round_seq IS that round's id —
        # there is no separate "current round" field to keep in sync.
        # Deposal adds the STUCK round's id to _shed_rounds —
        # suppression is then per-round, not per-generation: an earlier
        # round of the same generation whose results are still in the
        # completion pipeline was never shed, and suppressing it would
        # silently lose its verdicts.  The set grows by one per deposal
        # (bounded by distinct stalls, like the abandoned threads).
        self.round_seq = 0
        self._shed_rounds: set[int] = set()
        self._worker = threading.Thread(
            target=self._run, args=(0,), name=name, daemon=True
        )
        self._watchdog_stop = threading.Event()
        # Dispatch telemetry (read by benches/status).
        self.batches = 0
        self.entries = 0
        self.fill_dispatches = 0
        self.deadline_dispatches = 0
        self.shed_submits = 0
        self.shed_weight = 0
        self.stall_deposals = 0
        # Handoff fence (service.handoff_surrender): once True, every
        # non-force submit is refused — the admission-layer backstop
        # behind the service's typed SHED_FENCED gate, so a zombie
        # predecessor can never grow its queue after surrendering.
        self.fenced = False
        # Cumulative wall-clock with a round in flight (worker OR
        # cut-through inline) — the dispatcher-busy half of the device
        # telemetry; the tracer's device-busy gauge covers the chip
        # half.  Written only at round close (one float add per round).
        self.busy_seconds = 0.0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "BatchDispatcher":
        self._started = True
        self._worker.start()
        if self.stall_timeout_s > 0:
            threading.Thread(
                target=self._watch,
                name=f"{self._name}-watchdog",
                daemon=True,
            ).start()
        return self

    def stop(self) -> None:
        """Idempotent; safe before start() and when called twice."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
            self._done.notify_all()
            worker = self._worker
        self._watchdog_stop.set()
        if self._started and worker.is_alive():
            worker.join(timeout=5)

    # -- admission --------------------------------------------------------

    def submit(self, item: Any, weight: int = 1, force: bool = False,
               session: Any = None, nbytes: int = 0) -> bool:
        """Queue one item; False means the admission cap refused it (the
        caller owes the peer a typed SHED response — weight-0/control
        items pass ``force=True`` and are never refused).  ``session``
        (a transport.SessionState) charges the admitted weight (and
        ``nbytes`` payload bytes) to that session's DRR queue share;
        the charge is released wholesale when a round pops the queue."""
        with self._cond:
            if not force and self.fenced:
                self.shed_submits += 1
                self.shed_weight += weight
                return False
            if (
                not force
                and self.max_pending
                and self._pending_weight + weight > self.max_pending
            ):
                self.shed_submits += 1
                self.shed_weight += weight
                return False
            if not self._pending:
                self._oldest_ts = time.perf_counter()
            self._pending.append(item)
            self._pending_weight += weight
            self._pending_bytes += nbytes
            if session is not None:
                session.q_weight += weight
                session.q_bytes += nbytes
                self._q_sessions.add(session)
            self._cond.notify()
        return True

    def submit_many(self, items: list[tuple[Any, int]],
                    force: bool = False, session: Any = None) -> list[Any]:
        """Queue a pre-formed run of ``(item, weight)`` pairs under ONE
        lock trip — the shared-memory doorbell drain's admission path
        (a deep doorbell must not pay a lock round trip per frame).
        Admission is per item: the cap can refuse a suffix while
        admitting the prefix; refused items are RETURNED and the caller
        owes each a typed SHED response (exactly submit()'s contract).
        ``session`` charges admitted weight as in submit() — one drain
        is one session's frames, so one charge target covers the run.
        Entries may be ``(item, weight, nbytes)`` triples to charge
        payload bytes to the byte-weighted outstanding gauge."""
        refused: list[Any] = []
        with self._cond:
            admitted = False
            for entry in items:
                item, weight = entry[0], entry[1]
                nbytes = entry[2] if len(entry) > 2 else 0
                if not force and self.fenced:
                    self.shed_submits += 1
                    self.shed_weight += weight
                    refused.append(item)
                    continue
                if (
                    not force
                    and self.max_pending
                    and self._pending_weight + weight > self.max_pending
                ):
                    self.shed_submits += 1
                    self.shed_weight += weight
                    refused.append(item)
                    continue
                if not self._pending:
                    self._oldest_ts = time.perf_counter()
                self._pending.append(item)
                self._pending_weight += weight
                self._pending_bytes += nbytes
                if session is not None:
                    session.q_weight += weight
                    session.q_bytes += nbytes
                    self._q_sessions.add(session)
                admitted = True
            if admitted:
                self._cond.notify()
        return refused

    @property
    def pending_weight(self) -> int:
        return self._pending_weight

    def scale_admission(self, max_pending: int) -> None:
        """Re-point the global admission cap (the mesh width ladder's
        capacity coupling: a degraded rung shrinks the queue so
        overload sheds typed at the rung's ACTUAL capacity instead of
        queueing into deadline sheds).  Taken under the lock so an
        in-flight submit never reads a torn cap."""
        with self._cond:
            self.max_pending = int(max_pending)

    def oldest_age_s(self) -> float:
        """Age of the oldest queued item (0 when idle)."""
        with self._cond:
            if not self._pending:
                return 0.0
            return time.perf_counter() - self._oldest_ts

    def flush(self, timeout: float | None = None) -> bool:
        """Block until everything submitted so far has been processed.
        Condition-based (signalled at batch completion) — never a poll
        loop, and a deposed (stuck) round does not wedge it: deposal
        clears busy and signals.  Returns False on timeout."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._done:
            while self._pending or self._busy:
                if self._stopped:
                    break
                wait = None
                if deadline is not None:
                    wait = deadline - time.perf_counter()
                    if wait <= 0:
                        return False
                self._done.wait(wait)
        # One more beat for a cut-through round currently inline on a
        # reader thread (it holds the current in-process lock).
        lock = self._in_process_lock
        with lock:
            pass
        return True

    def thread_is_deposed(self) -> bool:
        """True when the CALLING thread is a dispatcher worker that has
        been deposed by the stall watchdog — its late sends must be
        suppressed (the stuck batch already received typed verdicts)."""
        gen = getattr(threading.current_thread(), "_disp_gen", None)
        return gen is not None and gen != self._gen

    def thread_round_is_shed(self) -> bool:
        """True when the CALLING thread carries a round id the watchdog
        shed (typed SHED verdicts already sent for the whole batch) —
        its sends for that round must be suppressed.  Covers both the
        stuck thread itself (worker or cut-through reader) and the send
        loop, which adopts each pipeline record's round id while
        emitting it."""
        rid = getattr(threading.current_thread(), "_disp_round", None)
        return rid is not None and rid in self._shed_rounds

    def begin_inline_round(self, batch: list[Any],
                           nbytes: int = 0) -> int | None:
        """Arm the stall watchdog for a cut-through round (caller holds
        the in-process lock).  Without this a device call hung inside
        an inline round on an otherwise IDLE service is invisible —
        _busy stays false, the watchdog skips every cycle, and the shim
        reader wedges with no typed reply and no quarantine.  Returns
        the round id, or None when a worker round is queued/in flight
        (the caller must line up behind it — claiming the round state
        here would clobber a concurrent _pop_locked's)."""
        with self._cond:
            if self._pending or self._busy:
                return None
            self._busy = True
            self._round_start = time.perf_counter()
            self._current_batch = batch
            self.round_seq += 1
            thread = threading.current_thread()
            thread._disp_round = self.round_seq
            # Cut-through bypasses the queue entirely: depth/age are 0
            # by construction; nbytes is the inline item's own payload.
            thread._disp_pop = {
                "trigger": "cut-through",
                "depth": 0,
                "age_s": 0.0,
                "bytes": int(nbytes),
            }
            return self.round_seq

    def end_inline_round(self, rid: int) -> None:
        """Close a cut-through round — but only if it still owns the
        round state: a worker pop (behind the held lock) or a deposal
        may have superseded it, and clearing _busy then would break the
        set-before-clear ordering the cut-through peek relies on."""
        with self._cond:
            if self.round_seq == rid:
                self._busy = False
                self._current_batch = None
                self.busy_seconds += time.perf_counter() - self._round_start
                self._done.notify_all()
                # The worker parks in _take while an inline round is
                # busy (it must not clobber the round state) — wake it
                # so work queued behind this round dispatches now.
                self._cond.notify_all()

    # -- worker -----------------------------------------------------------

    def _pop_locked(self, trigger: str = "idle-greedy") -> list[Any]:
        self._busy = True  # before the clear — see __init__ note
        self._round_start = time.perf_counter()
        self.round_seq += 1
        # _pop_locked runs on the worker thread itself (via _take), so
        # the round id can be recorded directly on it — and so can the
        # round's formation provenance (why the batch was issued, how
        # deep the queue was, how old its head was, bytes at issue),
        # which the service folds into the RoundTrace.  One stamp per
        # ROUND, never per entry.
        thread = threading.current_thread()
        thread._disp_round = self.round_seq
        age_s = (time.perf_counter() - self._oldest_ts
                 if self._pending else 0.0)
        thread._disp_pop = {
            "trigger": trigger,
            "depth": len(self._pending),
            "age_s": age_s,
            "bytes": self._pending_bytes,
        }
        # Sampled once per round: the queue's byte-weighted outstanding
        # work the instant it drains (bytes at issue).
        metrics.DrrOutstandingBytes.set(self._pending_bytes)
        batch = self._pending
        self._current_batch = batch
        self._pending = []
        self._pending_weight = 0
        self._pending_bytes = 0
        # The pop takes the WHOLE queue: every session's queued charge
        # drains with it (DRR share replenished at service pace).
        for sess in self._q_sessions:
            sess.q_weight = 0
            sess.q_bytes = 0
        self._q_sessions.clear()
        return batch

    def _take(self, my_gen: int) -> tuple[list[Any] | None, bool]:
        """Wait for fill or deadline; returns (batch, was_deadline).
        Returns (None, False) when this worker has been deposed."""
        with self._cond:
            while True:
                if self._gen != my_gen:
                    return None, False
                if self._busy:
                    # A cut-through inline round owns the round state
                    # (_round_start/round_seq/_current_batch) —
                    # the worker never sees its OWN round here (it
                    # clears _busy before re-entering _take).  Popping
                    # now would clobber the watchdog's view of the
                    # genuinely in-flight round: the watchdog would
                    # time the pop's (merely lock-blocked) batch,
                    # depose THAT, and the actually-stuck inline item
                    # would never be shed — its client wedged
                    # unboundedly.  Wait it out: end_inline_round and
                    # deposal both notify this condition.
                    self._cond.wait()
                    continue
                if self._stopped:
                    return self._pop_locked("flush"), False
                if self._pending_weight >= self.max_batch:
                    return self._pop_locked("size-full"), False
                if self._pending:
                    if self.timeout_s <= 0:  # greedy mode
                        return self._pop_locked("idle-greedy"), False
                    wait = self.timeout_s - (time.perf_counter() - self._oldest_ts)
                    if wait <= 0:
                        return self._pop_locked("deadline"), True
                    self._cond.wait(wait)
                else:
                    self._cond.wait()

    def _run(self, my_gen: int) -> None:
        threading.current_thread()._disp_gen = my_gen
        while True:
            batch, deadline = self._take(my_gen)
            if batch is None:
                return  # deposed while waiting
            if batch:
                # Capture the lock object: deposal swaps in a fresh one
                # for the replacement generation, so a stuck holder of
                # the old lock can never wedge the new worker.
                lock = self._in_process_lock
                with lock:
                    self.batches += 1
                    self.entries += len(batch)
                    if deadline:
                        self.deadline_dispatches += 1
                    else:
                        self.fill_dispatches += 1
                    try:
                        self.process(batch)
                    except Exception as exc:  # noqa: BLE001 — must survive
                        log.exception("batch process failed")
                        if (
                            self.on_batch_error is not None
                            and self._gen == my_gen
                        ):
                            try:
                                self.on_batch_error(batch, exc)
                            except Exception:  # noqa: BLE001
                                log.exception("on_batch_error failed")
            with self._cond:
                if self._gen != my_gen:
                    return  # deposed mid-round: a replacement owns the queue
                self._busy = False
                self._current_batch = None
                if batch:
                    self.busy_seconds += (
                        time.perf_counter() - self._round_start
                    )
                self._done.notify_all()
                if self._stopped and not self._pending:
                    return

    # -- stall watchdog ---------------------------------------------------

    def _watch(self) -> None:
        interval = max(min(self.stall_timeout_s / 4.0, 0.5), 0.01)
        while not self._watchdog_stop.wait(interval):
            with self._cond:
                if self._stopped:
                    return
                if not self._busy:
                    continue
                if (
                    time.perf_counter() - self._round_start
                    < self.stall_timeout_s
                ):
                    continue
                # A free in-process lock means process() already
                # RETURNED (its verdicts are sent) and the worker is
                # merely about to clear _busy — deposing now would send
                # duplicate SHED replies for served seqs.  Only a held
                # lock is a genuinely stuck round.
                lk = self._in_process_lock
                if lk.acquire(blocking=False):
                    lk.release()
                    continue
                # Depose: abandon the stuck worker+lock, hand the queue
                # to a fresh generation, and surface the stuck batch for
                # typed shed verdicts.
                batch = self._current_batch
                self._current_batch = None
                self._gen += 1
                self._busy = False
                # The stuck round's sends — from the abandoned thread
                # OR from pipeline records it already queued — are
                # suppressed per-round (see thread_round_is_shed).
                # round_seq is the stuck round's id: it only advances
                # while _busy is false, and this round is still busy.
                self._shed_rounds.add(self.round_seq)
                self._in_process_lock = threading.Lock()
                self.stall_deposals += 1
                self._worker = threading.Thread(
                    target=self._run,
                    args=(self._gen,),
                    name=f"{self._name}-g{self._gen}",
                    daemon=True,
                )
                self._worker.start()
                self._done.notify_all()
                # Wake any idle PREVIOUS-generation worker parked in
                # _take's cond wait (deposal during a cut-through round
                # never submits): it observes the gen bump and exits
                # instead of lingering until the next submit.
                self._cond.notify_all()
            log.error(
                "dispatch round stalled > %.1fs; worker deposed "
                "(generation %d)", self.stall_timeout_s, self._gen,
            )
            if self.on_stall is not None and batch:
                try:
                    self.on_stall(batch)
                except Exception:  # noqa: BLE001
                    log.exception("on_stall failed")
