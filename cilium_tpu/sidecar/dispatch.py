"""Adaptive fill-vs-deadline batch dispatcher.

Device dispatch is most efficient at full batches, but a request that
arrives into an idle service must not wait a full batch's worth of fill
time — the p99 budget is <1ms added latency (BASELINE.json north star).
The dispatcher implements the standard fill-vs-deadline tradeoff:

- a batch is dispatched immediately once pending work reaches
  ``max_batch`` entries (fill), or
- when the oldest pending entry has waited ``batch_timeout_ms``
  (deadline), whichever comes first.

``batch_timeout_ms=0`` selects GREEDY mode: the worker takes whatever
is pending the moment it frees up.  While a round is being processed,
arrivals coalesce naturally into the next round (self-adaptive batching
— steady-state round size ≈ arrival rate × round service time), and an
idle service adds zero queue wait.  This is the right mode when the
per-round device cost is small and local (co-located chip); the
deadline mode wins when each round pays a large fixed transport cost
worth amortizing across more entries.

The deadline timer arms when the first item lands in an empty queue, so
an idle service adds at most ``batch_timeout_ms`` + one device pass to
any request.  This is the consumer of ``DaemonConfig.batch_timeout_ms``
(utils/option.py) — the reference has no device batching; its nearest
analog is the per-request proxy dispatch in GoFilter::Instance::OnIO
(reference: envoy/cilium_proxylib.cc:125), which this component amortizes
across flows.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable


class BatchDispatcher:
    """Collects submitted items and hands batches to ``process`` on a
    dedicated worker thread.

    ``process(items)`` receives the pending list (oldest first).  Each
    item carries a ``weight`` (entry count for wire requests) counted
    toward the fill threshold.
    """

    def __init__(
        self,
        process: Callable[[list[Any]], None],
        max_batch: int = 2048,
        timeout_ms: float = 0.5,
        name: str = "verdict-dispatch",
    ):
        self.process = process
        self.max_batch = max_batch
        self.timeout_s = timeout_ms / 1000.0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[Any] = []
        self._pending_weight = 0
        self._oldest_ts = 0.0
        self._stopped = False
        self._in_process_lock = threading.Lock()
        # True from the moment the worker pops a batch in _take until it
        # finishes processing it.  Set BEFORE _pending is cleared (both
        # under _cond) so a lock-free reader that observes an empty
        # _pending is guaranteed to observe _busy=True for any popped
        # batch still in flight — the ordering the service's cut-through
        # path relies on to never overtake queued work.
        self._busy = False
        self._worker = threading.Thread(target=self._run, name=name, daemon=True)
        # Dispatch telemetry (read by benches/status).
        self.batches = 0
        self.entries = 0
        self.fill_dispatches = 0
        self.deadline_dispatches = 0

    def start(self) -> "BatchDispatcher":
        self._worker.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._worker.join(timeout=5)

    def submit(self, item: Any, weight: int = 1) -> None:
        with self._cond:
            if not self._pending:
                self._oldest_ts = time.perf_counter()
            self._pending.append(item)
            self._pending_weight += weight
            self._cond.notify()

    def flush(self) -> None:
        """Block until everything submitted so far has been processed."""
        while True:
            with self._cond:
                if not self._pending:
                    break
            time.sleep(0.0005)
        # One more beat for the batch currently in process().
        with self._in_process_lock:
            pass

    def _pop_locked(self) -> list[Any]:
        self._busy = True  # before the clear — see __init__ note
        batch = self._pending
        self._pending = []
        self._pending_weight = 0
        return batch

    def _take(self) -> tuple[list[Any], bool]:
        """Wait for fill or deadline; returns (batch, was_deadline)."""
        with self._cond:
            while True:
                if self._stopped:
                    return self._pop_locked(), False
                if self._pending_weight >= self.max_batch:
                    return self._pop_locked(), False
                if self._pending:
                    if self.timeout_s <= 0:  # greedy mode
                        return self._pop_locked(), False
                    wait = self.timeout_s - (time.perf_counter() - self._oldest_ts)
                    if wait <= 0:
                        return self._pop_locked(), True
                    self._cond.wait(wait)
                else:
                    self._cond.wait()

    def _run(self) -> None:
        while True:
            batch, deadline = self._take()
            if batch:
                with self._in_process_lock:
                    self.batches += 1
                    self.entries += len(batch)
                    if deadline:
                        self.deadline_dispatches += 1
                    else:
                        self.fill_dispatches += 1
                    try:
                        self.process(batch)
                    except Exception:  # noqa: BLE001 — worker must survive
                        import logging

                        logging.getLogger(__name__).exception(
                            "batch process failed"
                        )
            self._busy = False
            if self._stopped and not batch:
                return
            if self._stopped:
                with self._cond:
                    if not self._pending:
                        return
