"""Mixed-path throughput benchmark: the slow/oracle paths under a
realistic traffic mix.

The headline throughput configs exercise only the vectorized fast path
(single complete request-direction frames).  Real proxy traffic also
carries partial frames (a frame split across reads — carried state),
pipelined frames (several frames in one read), and reply-direction
bytes — all of which the reference's in-process parser handles in the
same code path (proxylib/proxylib/connection.go:118) but which this
architecture routes through the batch engines' wave path and the
in-process oracle.  This bench measures steady-state verdicts/s for a
configurable mix and reports the per-path split, so a regression in
the non-fast paths cannot hide behind the fast-path headline.

Closed loop: W rounds in flight; each round is one DataBatch over the
connection pool with the mix applied per-connection:
  - fast conns:      one complete frame per round (entrywise fast path,
                     one bucketed device call per round)
  - partial conns:   frames split across two rounds (engine buffering,
                     wave path; a verdict every second round)
  - pipelined conns: two complete frames in one entry (wave path, two
                     verdicts per round)
  - reply conns:     request frame + reply-direction bytes (oracle /
                     engine reply handling)
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..proxylib.types import FilterResult
from ..utils.option import DaemonConfig
from . import wire
from .client import SidecarClient
from .service import VerdictService


class MixBench:
    def __init__(
        self,
        socket_path: str,
        pool: int = 8192,
        frac_partial: float = 0.10,
        frac_pipelined: float = 0.05,
        frac_reply: float = 0.05,
        batch_flows: int = 8192,
        verdict_device: str = "default",
    ) -> None:
        from cilium_tpu.proxylib import (
            NetworkPolicy,
            PortNetworkPolicy,
            PortNetworkPolicyRule,
        )

        self.pool = pool
        n_partial = int(pool * frac_partial)
        n_pipe = int(pool * frac_pipelined)
        n_reply = int(pool * frac_reply)
        n_fast = pool - n_partial - n_pipe - n_reply
        # Conn-id layout: [fast | partial | pipelined | reply]
        self.n_fast, self.n_partial, self.n_pipe, self.n_reply = (
            n_fast, n_partial, n_pipe, n_reply,
        )

        policy = NetworkPolicy(
            name="mixbench",
            policy=2,
            ingress_per_port_policies=[
                PortNetworkPolicy(
                    port=80,
                    rules=[
                        PortNetworkPolicyRule(
                            l7_proto="r2d2",
                            l7_rules=[
                                {"cmd": "READ", "file": "/public/.*"},
                                {"cmd": "HALT"},
                            ],
                        )
                    ],
                )
            ],
        )
        # batch_timeout_ms > 0 selects the completion-pipeline mode
        # (overlapped readbacks) — the right mode for a high-RTT device
        # link; greedy/inline mode would serialize one readback per
        # round.
        cfg = DaemonConfig(
            batch_flows=batch_flows,
            batch_timeout_ms=0.25,
            batch_width=64,
            verdict_device=verdict_device,
        )
        self._policy = policy
        self.service = VerdictService(socket_path, cfg).start()
        self.client = SidecarClient(socket_path, timeout=600.0)
        self.module = self.client.open_module([])
        assert self.client.policy_update(self.module, [policy]) == int(
            FilterResult.OK
        )
        for cid in range(1, pool + 1):
            res, _ = self.client.new_connection(
                self.module, "r2d2", cid, True, 1, 2,
                "1.1.1.1:1", "2.2.2.2:80", "mixbench",
            )
            assert res == int(FilterResult.OK), res

        # Frame corpus (mixed allow/deny), pre-padded to device rows so
        # the per-round matrix build is numpy indexing, not Python.
        rng = np.random.default_rng(11)
        self.frames = []
        for i in range(pool):
            roll = rng.random()
            if roll < 0.4:
                self.frames.append(f"READ /public/f{i % 997}.txt\r\n".encode())  # lint: disable=R7 -- one-time corpus setup, never inside a timed window
            elif roll < 0.55:
                self.frames.append(b"HALT\r\n")  # lint: disable=R7 -- one-time corpus setup, never inside a timed window
            else:
                self.frames.append(f"READ /private/f{i % 997}\r\n".encode())  # lint: disable=R7 -- one-time corpus setup, never inside a timed window
        self.pool_rows = np.zeros((pool, 64), np.uint8)
        self.pool_lens = np.zeros((pool,), np.uint32)
        for i, f in enumerate(self.frames):
            self.pool_rows[i, : len(f)] = np.frombuffer(f, np.uint8)
            self.pool_lens[i] = len(f)
        # Columnar round-build state (the generator must measure the
        # SERVICE, not per-entry dict/list churn on the harness side):
        # the conn-id layout is constant across rounds, frame bytes are
        # gathered from the flat pool with sidecar/reasm.py's ragged
        # scatter helpers, and the reply tail is one constant tile.
        self._pool_flat = self.pool_rows.reshape(-1)
        self._pool_lens64 = self.pool_lens.astype(np.int64)
        self._p_cids = np.arange(
            n_fast + 1, n_fast + n_partial + 1, dtype=np.int64
        )
        self._pi_cids = np.arange(
            n_fast + n_partial + 1, n_fast + n_partial + n_pipe + 1,
            dtype=np.int64,
        )
        n_re0 = n_fast + n_partial + n_pipe
        self._re_cids = np.arange(
            n_re0 + 1, n_re0 + n_reply + 1, dtype=np.int64
        )
        self._data_cids = np.concatenate(
            (self._p_cids, self._pi_cids, self._re_cids)
        ).astype(np.uint64)
        self._data_flags = np.concatenate((
            np.zeros(n_partial + n_pipe, np.uint8),
            np.full(n_reply, wire.FLAG_REPLY, np.uint8),
        ))
        self._reply_tail = np.tile(
            np.frombuffer(b"OK\r\n", np.uint8), n_reply
        )

    def _build_round(self, round_idx: int):
        """One round = one complete-flag MATRIX batch (the fast conns —
        the C++ edge owns framing and ships frames it completed as
        kMsgDataMatrix complete=1, so they ride the vec path) plus one
        DataBatch carrying everything the edge could NOT frame: partial
        reads, pipelined reads, reply-direction bytes.  Pure columnar:
        per-category segment (start, len) arrays into the flat frame
        pool, one ragged gather for the blob — no per-entry Python
        (the bench measures the service, not the harness).  Returns
        (matrix, data_batch, n_verdict_frames, split)."""
        from .reasm import gather_segments

        # fast conns -> matrix rows (pure numpy: pool indexing)
        m_ids = np.arange(1, self.n_fast + 1, dtype=np.uint64)
        sel = (np.arange(1, self.n_fast + 1) + round_idx) % self.pool
        m_rows = self.pool_rows[sel]
        m_lens = self.pool_lens[sel]

        # partial: half a frame per round (verdict lands on odd rounds)
        p_sel = (self._p_cids + round_idx // 2) % self.pool
        p_flen = self._pool_lens64[p_sel]
        p_half = p_flen // 2
        if round_idx % 2 == 0:
            p_start = p_sel * 64
            p_len = p_half
            partial_done = 0
        else:
            p_start = p_sel * 64 + p_half
            p_len = p_flen - p_half
            partial_done = self.n_partial
        # pipelined: two complete frames in one entry (two segments)
        s1 = (self._pi_cids + round_idx) % self.pool
        s2 = (self._pi_cids + round_idx + 1) % self.pool
        l1 = self._pool_lens64[s1]
        l2 = self._pool_lens64[s2]
        pi_len = l1 + l2
        lengths = np.concatenate((
            p_len, pi_len, np.full(self.n_reply, 4, np.int64),
        ))
        offs = np.concatenate(
            ([0], np.cumsum(lengths))
        ).astype(np.int64)
        blob = np.empty(int(offs[-1]), np.uint8)
        # One ragged gather covers the partial halves and both
        # pipelined segments; the constant reply tail is a block copy.
        seg_starts = np.empty(self.n_partial + 2 * self.n_pipe, np.int64)
        seg_lens = np.empty_like(seg_starts)
        seg_dst = np.empty_like(seg_starts)
        np_, npi = self.n_partial, self.n_pipe
        seg_starts[:np_] = p_start
        seg_lens[:np_] = p_len
        seg_dst[:np_] = offs[:np_]
        seg_starts[np_ : np_ + 2 * npi : 2] = s1 * 64
        seg_starts[np_ + 1 : np_ + 2 * npi : 2] = s2 * 64
        seg_lens[np_ : np_ + 2 * npi : 2] = l1
        seg_lens[np_ + 1 : np_ + 2 * npi : 2] = l2
        seg_dst[np_ : np_ + 2 * npi : 2] = offs[np_ : np_ + npi]
        seg_dst[np_ + 1 : np_ + 2 * npi : 2] = offs[np_ : np_ + npi] + l1
        gather_segments(self._pool_flat, seg_starts, seg_lens,
                        out=blob, dst_starts=seg_dst)
        blob[int(offs[np_ + npi]) :] = self._reply_tail

        frames_done = (
            self.n_fast + partial_done + 2 * self.n_pipe + self.n_reply
        )
        split = {
            "fast": self.n_fast,
            "partial": partial_done,
            "pipelined": 2 * self.n_pipe,
            "reply": self.n_reply,
        }
        matrix = (m_ids, m_lens, m_rows.tobytes())
        data = (
            self._data_cids, self._data_flags,
            lengths.astype(np.uint32), blob.tobytes(),
        )
        return matrix, data, frames_done, split

    def _send_round(self, seq: int, round_idx: int):
        """Ship one round as (matrix seq, data seq+1); returns
        (frames, split)."""
        matrix, data, nf, split = self._build_round(round_idx)
        m_ids, m_lens, m_rows = matrix
        self.client.send_matrix(seq, 64, m_ids, m_lens, m_rows,
                                complete=True)
        ids, fl, lens, blob = data
        self.client.send_batch(seq + 1, ids, fl, lens, blob)
        return nf, split

    def run(self, duration_s: float = 12.0, warmup_rounds: int = 4) -> dict:
        recv_seqs: dict[int, float] = {}
        evt = threading.Event()

        def on_verdict(vb):
            recv_seqs[vb.seq] = time.perf_counter()
            evt.set()

        self.client.verdict_callback = on_verdict

        # Warmup (compiles every bucket the mix touches).
        seq = 1
        for r in range(warmup_rounds):
            self._send_round(seq, r)
            deadline = time.monotonic() + 600
            while seq + 1 not in recv_seqs and time.monotonic() < deadline:
                evt.wait(1.0)
                evt.clear()
            assert seq + 1 in recv_seqs, "warmup round lost"
            seq += 2

        # Timed closed loop, two rounds in flight (a round completes
        # when BOTH its seqs answered).
        t0 = time.perf_counter()
        last_progress = time.monotonic()
        frames_total = 0
        split_total = {"fast": 0, "partial": 0, "pipelined": 0, "reply": 0}
        inflight: dict[int, int] = {}  # matrix seq -> frame count
        round_idx = warmup_rounds
        rounds = 0
        while time.perf_counter() - t0 < duration_s or inflight:
            while (
                len(inflight) < 2
                and time.perf_counter() - t0 < duration_s
            ):
                nf, split = self._send_round(seq, round_idx)
                inflight[seq] = nf
                for k, v in split.items():
                    split_total[k] += v
                seq += 2
                round_idx += 1
                rounds += 1
            done = [
                s for s in inflight
                if s in recv_seqs and s + 1 in recv_seqs
            ]
            for s in done:
                frames_total += inflight.pop(s)
                last_progress = time.monotonic()
            if not done:
                evt.wait(0.05)
                evt.clear()
                if time.monotonic() - last_progress > 120:
                    raise TimeoutError(
                        f"mixbench stalled: rounds {sorted(inflight)} "
                        f"never answered"
                    )
        elapsed = time.perf_counter() - t0
        self.client.verdict_callback = None
        slow_frames = (
            split_total["partial"] + split_total["pipelined"]
            + split_total["reply"]
        )
        # Columnar-reassembler engagement (sidecar/reasm.py): the bench
        # reports it so the floor assertion can prove the slow lane was
        # actually served columnar, not silently falling back scalar.
        reasm = self.service.status().get("reasm") or {}
        return {
            "verdicts_per_sec": frames_total / elapsed,
            "frames": frames_total,
            "rounds": rounds,
            "elapsed_s": elapsed,
            "split": split_total,
            "slow_fraction": slow_frames / max(
                slow_frames + split_total["fast"], 1
            ),
            "reasm_rounds": int(reasm.get("rounds", 0)),
            "reasm_frames": int(reasm.get("frames", 0)),
            "reasm_fallbacks": dict(reasm.get("fallbacks", {})),
        }

    def oracle_rate(self, rounds: int = 6) -> float:
        """The reference-architecture comparison point: the SAME mixed
        entry stream fed through the ported in-process streaming parser
        (reference: proxylib/proxylib/connection.go:118 handles
        complete, partial, pipelined, and reply data in one code
        path).  Frames/s on this host, single-threaded."""
        from ..proxylib import instance as pl

        mod = pl.open_module([], True)
        ins = pl.find_instance(mod)
        ins.policy_update([self._policy])
        conns = {}
        for cid in range(1, self.pool + 1):
            res, conn = pl.on_new_connection(
                mod, "r2d2", 1_000_000 + cid, True, 1, 2,
                "1.1.1.1:1", "2.2.2.2:80", "mixbench",
            )
            conns[cid] = conn
        frames_total = 0
        t0 = time.perf_counter()
        for r in range(rounds):
            matrix, data, nf, _split = self._build_round(r)
            m_ids, m_lens, m_rows = matrix
            rows = np.frombuffer(m_rows, np.uint8).reshape(-1, 64)
            for k in range(len(m_ids)):
                ops: list = []
                c = conns[int(m_ids[k])]
                c.on_data(
                    False, False, [rows[k, : m_lens[k]].tobytes()], ops
                )
                c.reply_buf.take()
            ids, fl, lens, blob = data
            offs = np.concatenate(([0], np.cumsum(lens.astype(np.int64))))
            for k in range(len(ids)):
                ops = []
                c = conns[int(ids[k])]
                c.on_data(
                    bool(fl[k] & wire.FLAG_REPLY), False,
                    [blob[offs[k]:offs[k + 1]]], ops,
                )
                c.reply_buf.take()
            frames_total += nf
        elapsed = time.perf_counter() - t0
        pl.close_module(mod)
        return frames_total / elapsed

    def close(self) -> None:
        self.client.close()
        self.service.stop()


class FlowCacheBench:
    """Long-lived-flow traffic shape for the established-flow verdict
    cache (PR 12): a pool of conns that each ship one whole frame per
    round for the run's whole duration — the steady state the cache is
    built for.  ``cacheable_frac`` of the pool carries identity 1,
    admitted by a byte-FREE rule row (pure "allow these peers" —
    invariant-allow, armed at registration); the rest carry identity 2,
    admitted only by byte-constrained rows (no claim — every frame
    needs the device).  Each round ships the two groups as separate
    complete-flag matrix batches so the shim's whole-batch tier can
    answer the cacheable group locally (bytes never cross the
    transport) while the control group exercises the full device path.

    Run cache-on vs cache-off (both knobs) over identical traffic: the
    delta IS the cache, and ``bytes_pushed`` proves the shim-side
    short-circuit at the byte level."""

    def __init__(
        self,
        socket_path: str,
        pool: int = 4096,
        cacheable_frac: float = 0.8,
        flow_cache: bool = True,
        batch_flows: int = 8192,
        verdict_device: str = "default",
    ) -> None:
        from cilium_tpu.proxylib import (
            NetworkPolicy,
            PortNetworkPolicy,
            PortNetworkPolicyRule,
        )

        self.pool = pool
        self.n_cacheable = int(pool * cacheable_frac)
        self.n_control = pool - self.n_cacheable
        policy = NetworkPolicy(
            name="flowcache",
            policy=2,
            ingress_per_port_policies=[
                PortNetworkPolicy(
                    port=80,
                    rules=[
                        # Byte-free row: identity 1 is allowed whatever
                        # it sends — the invariant-allow class (pure
                        # L3/L4 admission expressed as an L7 rule set).
                        PortNetworkPolicyRule(
                            remote_policies=[1], l7_proto="r2d2",
                            l7_rules=[{}],
                        ),
                        # Byte-constrained rows: identity 2 must be
                        # inspected per frame.
                        PortNetworkPolicyRule(
                            remote_policies=[2], l7_proto="r2d2",
                            l7_rules=[
                                {"cmd": "READ", "file": "/public/.*"},
                                {"cmd": "HALT"},
                            ],
                        ),
                    ],
                )
            ],
        )
        cfg = DaemonConfig(
            batch_flows=batch_flows,
            batch_timeout_ms=0.25,
            batch_width=64,
            verdict_device=verdict_device,
            flow_cache=flow_cache,
        )
        self.flow_cache = flow_cache
        self.service = VerdictService(socket_path, cfg).start()
        self.client = SidecarClient(
            socket_path, timeout=600.0, flow_cache=flow_cache
        )
        self.module = self.client.open_module([])
        assert self.client.policy_update(self.module, [policy]) == int(
            FilterResult.OK
        )
        for cid in range(1, pool + 1):
            remote = 1 if cid <= self.n_cacheable else 2
            res, _ = self.client.new_connection(
                self.module, "r2d2", cid, True, remote, 2,
                "1.1.1.1:1", "2.2.2.2:80", "flowcache",
            )
            assert res == int(FilterResult.OK), res
        # One whole frame per conn per round, pre-padded (columnar
        # round build like MixBench — the bench measures the seam).
        rng = np.random.default_rng(12)
        self.pool_rows = np.zeros((pool, 64), np.uint8)
        self.pool_lens = np.zeros((pool,), np.uint32)
        for i in range(pool):
            if i < self.n_cacheable:
                f = f"READ /lived/f{i % 997}.txt\r\n".encode()
            elif rng.random() < 0.6:
                f = f"READ /public/f{i % 997}.txt\r\n".encode()
            else:
                f = b"HALT\r\n"
            self.pool_rows[i, : len(f)] = np.frombuffer(f, np.uint8)
            self.pool_lens[i] = len(f)
        self._a_ids = np.arange(
            1, self.n_cacheable + 1, dtype=np.uint64
        )
        self._b_ids = np.arange(
            self.n_cacheable + 1, pool + 1, dtype=np.uint64
        )

    def _send_round(self, seq: int) -> int:
        a, b = self.n_cacheable, self.n_control
        if a:
            self.client.send_matrix(
                seq, 64, self._a_ids, self.pool_lens[:a],
                self.pool_rows[:a].tobytes(), complete=True,
            )
        if b:
            self.client.send_matrix(
                seq + 1, 64, self._b_ids, self.pool_lens[a:],
                self.pool_rows[a:].tobytes(), complete=True,
            )
        return a + b

    def run(self, duration_s: float = 8.0, warmup_rounds: int = 3) -> dict:
        recv: dict[int, float] = {}
        evt = threading.Event()

        def on_verdict(vb):
            recv[vb.seq] = time.perf_counter()
            evt.set()

        self.client.verdict_callback = on_verdict

        def expected(s: int) -> tuple:
            # Only the seqs _send_round actually ships: an all-cacheable
            # (or all-control) pool sends one batch per round, and
            # waiting on the phantom twin would wedge the whole run.
            return tuple(
                x for x, n in ((s, self.n_cacheable),
                               (s + 1, self.n_control)) if n
            )

        seq = 1
        for _ in range(warmup_rounds):
            self._send_round(seq)
            deadline = time.monotonic() + 600
            while (
                any(s not in recv for s in expected(seq))
                and time.monotonic() < deadline
            ):
                evt.wait(1.0)
                evt.clear()
            assert all(s in recv for s in expected(seq)), \
                "warmup round lost"
            seq += 2
        bytes0 = self.client.bytes_pushed
        hits0 = self.client.cache_hits
        t0 = time.perf_counter()
        frames_total = 0
        inflight: dict[int, int] = {}
        last_progress = time.monotonic()
        while time.perf_counter() - t0 < duration_s or inflight:
            while (
                len(inflight) < 2
                and time.perf_counter() - t0 < duration_s
            ):
                nf = self._send_round(seq)
                inflight[seq] = nf
                seq += 2
            done = [
                s for s in inflight
                if all(x in recv for x in expected(s))
            ]
            for s in done:
                frames_total += inflight.pop(s)
                last_progress = time.monotonic()
            if not done:
                evt.wait(0.05)
                evt.clear()
                if time.monotonic() - last_progress > 120:
                    raise TimeoutError(
                        f"flow_cache bench stalled: {sorted(inflight)}"
                    )
        elapsed = time.perf_counter() - t0
        self.client.verdict_callback = None
        shim_hits = self.client.cache_hits - hits0
        svc = self.service.status().get("flow_cache") or {}
        svc_hits = int(svc.get("hits", 0))
        svc_miss = int(svc.get("misses", 0))
        hits = shim_hits + svc_hits
        return {
            "verdicts_per_sec": frames_total / elapsed,
            "frames": frames_total,
            "elapsed_s": elapsed,
            "hit_rate": hits / max(hits + svc_miss, 1),
            "shim_hits": shim_hits,
            "service_hits": svc_hits,
            "bytes_pushed": self.client.bytes_pushed - bytes0,
            "armed": int(svc.get("armed", 0)),
            "invalidations": int(svc.get("invalidations", 0)),
        }

    def close(self) -> None:
        self.client.close()
        self.service.stop()
