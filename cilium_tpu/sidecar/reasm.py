"""Columnar frame reassembler for the mixed-path slow lane.

BENCH_NOTES r5 measured the honest mixed-path number at ~122k
verdicts/s against the 21.7M/s vec headline and attributed the gap to
~25µs/entry of host Python on the slow 20%: `feed` (per-entry buffer
append), frame extraction (per-entry `bytes.find` loops), `settle_entry`
(per-entry op emission) and per-entry response assembly.  This module
replaces that per-ENTRY work with a handful of array passes per ROUND
(the Libra / receive-side-dispatching shape from PAPERS.md: move
per-message byte shuffling into batched, layout-aware bulk operations):

- **Byte arena.**  Per-connection carry state (the partial frame a read
  left behind) lives in ONE contiguous numpy buffer with per-conn
  (offset, length) slots — not a Python ``bytearray`` per flow.  Slots
  are bump-allocated and compacted; per-conn totals stay bounded by the
  existing ``max_flow_buffer`` cap (overflow is the same typed
  DROP+ERROR contract as the scalar engines).
- **Vectorized ingest.**  A whole round's DataBatch payloads are
  appended to their conns' carries in one ragged scatter (carry bytes
  and payload bytes gathered into a round-local stream).
- **Vectorized framing.**  Frame boundaries are found with one scan
  over the stream — CRLF for r2d2/memcached-class protocols,
  length-prefixed (kafka/cassandra-class) via a per-frame-rank
  vectorized walk — with hits that straddle entry boundaries rejected
  columnar.
- **Columnar emission.**  Frame splitting and response-op assembly
  produce (entry, frame_offset, frame_len, verdict-slot) arrays that
  feed the service's single issued-not-read-back model call directly,
  and the finish half renders ops/injects/flow-records as array
  scatters.

The scalar engine path (`feed`/`feed_extract`/`settle_entry` in
runtime/batch.py) survives unchanged as the oracle/fallback rung: the
service routes anything the columnar path cannot prove safe (reply
direction, end_stream, demoted/stale conns, duplicate conns in one
round, non-CRLF engines) through it, and parity tests assert the two
paths are byte-identical in ops, injects and flow records.
"""

from __future__ import annotations

import numpy as np

from ..proxylib.types import DROP, ERROR, MORE, PASS, OpError

OP_PASS = int(PASS)
OP_DROP = int(DROP)
OP_MORE = int(MORE)
OP_ERROR = int(ERROR)
ERR_FRAME_LEN = int(OpError.ERROR_INVALID_FRAME_LENGTH)

# Framing kinds of the columnar feed contract (engine.reasm_spec()).
# Kinds with a registered Framing in ``FRAMINGS`` (bottom of module)
# ride the columnar lane; anything else serves scalar.
FRAMING_CRLF = "crlf"
FRAMING_DNS = "dns"
FRAMING_LENGTH_PREFIX = "length_prefix"


# --- ragged gather/scatter primitives ------------------------------------

def ragged_indices(starts, lens) -> np.ndarray:
    """Flat gather indices for segments ``(starts[i], lens[i])`` — the
    vectorized equivalent of concatenating ``arange(s, s+l)`` per
    segment, built with two cumsum passes instead of a Python loop.
    Zero-length segments are allowed (they contribute nothing)."""
    starts = np.asarray(starts, np.int64)
    lens = np.asarray(lens, np.int64)
    nz = lens > 0
    if not nz.all():
        starts = starts[nz]
        lens = lens[nz]
    if len(lens) == 0:
        return np.empty(0, np.int64)
    total = int(lens.sum())
    step = np.ones(total, np.int64)
    ends = np.cumsum(lens)
    step[0] = starts[0]
    if len(lens) > 1:
        step[ends[:-1]] = starts[1:] - (starts[:-1] + lens[:-1] - 1)
    return np.cumsum(step)


def gather_segments(src, starts, lens, out=None, dst_starts=None):
    """Bulk-copy segments out of ``src``: contiguous into a fresh (or
    provided) buffer when ``dst_starts`` is None, else scattered to the
    given destination offsets.  A few array passes total, independent
    of the segment count."""
    src = np.asarray(src)
    lens = np.asarray(lens, np.int64)
    total = int(lens.sum())
    si = ragged_indices(starts, lens)
    if dst_starts is None:
        if out is None:
            out = np.empty(total, src.dtype)
        out[:total] = src[si]
        return out
    out[ragged_indices(dst_starts, lens)] = src[si]
    return out


# --- frame-boundary scanners ---------------------------------------------

def segments_end_crlf(blob: np.ndarray, starts: np.ndarray,
                      lengths: np.ndarray) -> np.ndarray:
    """[n] bool — each segment is >= 2 bytes and its LAST two bytes are
    CRLF.  The verdict cache's frame-alignment gate (service Phase-A
    mask and the shim's pre-push check): a short-circuit must only ever
    cover whole frames, so an epoch flip or disarm at ANY point leaves
    the flow parseable from a frame boundary.  Like rows_end_crlf, the
    blob bound is part of the gate: a malformed start/length must read
    as a miss, never fancy-index past the blob."""
    n = len(lengths)
    if n == 0 or len(blob) < 2:
        return np.zeros(n, bool)
    li = np.asarray(lengths, np.int64)
    st = np.asarray(starts, np.int64)
    ok = (li >= 2) & (st >= 0) & (st + li <= len(blob))
    ends = np.where(ok, st + li, 2)
    return ok & (blob[ends - 2] == 13) & (blob[ends - 1] == 10)


def rows_end_crlf(rows: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """[n] bool — each padded row's payload is >= 2 bytes, fits the row
    width, and ends with CRLF.  The matrix-batch twin of
    segments_end_crlf, and like it THE frame-alignment gate definition
    for the verdict cache: the width bound is part of the gate (a
    malformed length must read as a miss, never fancy-index past the
    row)."""
    n = len(lengths)
    if n == 0 or rows.shape[1] < 2:
        return np.zeros(n, bool)
    li = np.asarray(lengths, np.int64)
    ok = (li >= 2) & (li <= rows.shape[1])
    le = np.where(ok, li, 2)
    ar = np.arange(n)
    return ok & (rows[ar, le - 2] == 13) & (rows[ar, le - 1] == 10)


def scan_crlf(stream: np.ndarray, ends: np.ndarray):
    """All CRLF positions ``p`` (``stream[p]==13 and stream[p+1]==10``)
    that lie wholly inside one entry.  Entries are contiguous:
    entry ``i`` spans ``[ends[i-1], ends[i])``.  A CR that is an
    entry's last byte must NOT pair with the next entry's leading LF —
    those straddling hits are rejected columnar (the scalar path never
    sees them because it scans per-conn buffers).  Returns
    ``(hits, entry_of_hit)``, both ascending."""
    ends = np.asarray(ends, np.int64)
    if len(stream) < 2:
        z = np.empty(0, np.int64)
        return z, z
    hits = np.flatnonzero((stream[:-1] == 13) & (stream[1:] == 10))
    if len(hits) == 0:
        return hits, hits
    e = np.searchsorted(ends, hits, side="right")
    keep = hits + 1 < ends[e]
    return hits[keep], e[keep]


def scan_length_prefixed(stream, offs, ends, frame_len_fn):
    """Frame boundaries for length-prefixed protocols (kafka/cassandra
    class).  Each pass computes the next boundary of EVERY still-active
    entry at once, so the Python loop runs max-frames-per-entry times,
    not once per frame.  ``frame_len_fn(stream, pos, avail)`` returns
    the total frame length (header included) per position, or -1 while
    the header is incomplete.  Returns ``(f_entry, f_start, f_len)``
    sorted by entry then stream order."""
    offs = np.asarray(offs, np.int64)
    ends = np.asarray(ends, np.int64)
    pos = offs.copy()
    alive = np.flatnonzero(ends > offs)
    out_e: list = []
    out_s: list = []
    out_l: list = []
    while len(alive):
        avail = ends[alive] - pos[alive]
        fl = np.asarray(frame_len_fn(stream, pos[alive], avail), np.int64)
        # A non-positive frame length (a malformed header a custom
        # reader maps to <= 0 — attacker-controllable bytes) means no
        # forward progress is possible for that entry: it simply stops
        # completing frames this round and its bytes stay as residue,
        # where the per-conn cap turns a wedged stream into the typed
        # overflow DROP+ERROR.  The scanner itself must stay TOTAL —
        # an exception here would abort the whole columnar round and
        # leak every other entry's answer (lint R15).
        done = (fl > 0) & (fl <= avail)
        if not done.any():
            break
        idx = alive[done]
        out_e.append(idx)  # lint: disable=R7 -- per frame-RANK (max frames per entry), never per entry: each pass is one vectorized step over every active entry
        out_s.append(pos[idx].copy())  # lint: disable=R7 -- see above: per-pass accumulator, not per-entry work
        out_l.append(fl[done])  # lint: disable=R7 -- see above: per-pass accumulator, not per-entry work
        pos[idx] += fl[done]
        alive = idx[pos[idx] < ends[idx]]
    if not out_e:
        z = np.empty(0, np.int64)
        return z, z, z
    f_entry = np.concatenate(out_e)
    f_start = np.concatenate(out_s)
    f_len = np.concatenate(out_l)
    order = np.lexsort((f_start, f_entry))
    return f_entry[order], f_start[order], f_len[order]


def length_prefix_reader(header_bytes: int, length_offset: int,
                         length_size: int = 4, big_endian: bool = True,
                         extra: int = 0):
    """``frame_len_fn`` factory for the common length-prefix layouts:
    total frame length = ``header_bytes`` + the ``length_size``-byte
    integer at ``length_offset`` (+ ``extra``).  Covers the cassandra
    v3/v4 frame (9-byte header, u32 body length at offset 5) and the
    kafka wire frame (4-byte big-endian size prefix)."""

    def fn(stream, pos, avail):
        out = np.full(len(pos), -1, np.int64)
        have = avail >= header_bytes
        if have.any():
            p = pos[have] + length_offset
            val = np.zeros(len(p), np.int64)
            for k in range(length_size):
                shift = (
                    (length_size - 1 - k) if big_endian else k
                ) * 8
                val |= stream[p + k].astype(np.int64) << shift
            out[have] = header_bytes + val + extra
        return out

    return fn


# --- per-framing dispatch -------------------------------------------------
#
# PR 10 gated the columnar lane on ``reasm_spec() == "crlf"``; this
# table lifts that gate into a per-framing dispatch: each Framing packs
# the scanner (ingest), the whole-frame alignment gates (the verdict
# cache's frame-boundary contract, per tier: round segments, matrix
# rows, single host payloads) and the per-denied-frame reply inject.
# Every future length-prefixed engine (HTTP/2-gRPC 9-byte headers,
# cassandra, kafka) lands by registering a Framing here and declaring
# its kind from ``reasm_spec()`` — no new service code.

class Framing:
    """One framing kind of the columnar feed contract."""

    kind = ""
    err_inject = b""  # reply bytes per denied frame (engine DENY_INJECT)

    def scan(self, stream, offs, ends):
        """Complete frames wholly inside entries: ``(f_entry, f_start,
        f_len)`` sorted by entry then stream order, frames contiguous
        from each entry's offset."""
        raise NotImplementedError

    def segments_aligned(self, blob, starts, lengths):
        """[n] bool — each segment is >= 1 whole frame ending exactly
        at the segment end (the cache tiers' frame-alignment gate: a
        short-circuit must only ever cover whole frames)."""
        raise NotImplementedError

    def rows_aligned(self, rows, lengths):
        """Matrix-row twin of segments_aligned (width bound included:
        a malformed length reads as a miss, never out of the row)."""
        n, w = rows.shape
        li = np.asarray(lengths, np.int64)
        ok = (li >= 1) & (li <= w)
        starts = np.arange(n, dtype=np.int64) * w
        return ok & self.segments_aligned(
            rows.reshape(-1), starts, np.where(ok, li, 0)
        )

    def payload_aligned(self, data: bytes) -> bool:
        """Single-payload (host bytes) twin of segments_aligned — the
        scalar classifier's per-entry cache gate."""
        raise NotImplementedError

    def payload_single_frame(self, data: bytes) -> bool:
        """Exactly ONE whole frame — the vectorized fast lane's
        per-entry gate."""
        raise NotImplementedError

    def segments_single_frame(self, blob, offs, lengths):
        """[n] bool — each segment is exactly one whole frame (the
        whole-batch vec-eligibility gate)."""
        raise NotImplementedError

    def rows_single_frame(self, rows, lengths):
        """Matrix-row twin of segments_single_frame."""
        n, w = rows.shape
        li = np.asarray(lengths, np.int64)
        ok = (li >= 1) & (li <= w)
        starts = np.arange(n, dtype=np.int64) * w
        return ok & self.segments_single_frame(
            rows.reshape(-1), starts, np.where(ok, li, 0)
        )


class CrlfFraming(Framing):
    """CRLF-delimited lines (r2d2, text memcached class)."""

    kind = FRAMING_CRLF
    err_inject = b"ERROR\r\n"

    def scan(self, stream, offs, ends):
        offs = np.asarray(offs, np.int64)
        hits, e_of = scan_crlf(stream, ends)
        nf = len(hits)
        first = np.ones(nf, bool)
        prev = np.zeros(nf, np.int64)
        if nf:
            first[1:] = e_of[1:] != e_of[:-1]
            prev[1:] = hits[:-1]
        f_start = np.where(first, offs[e_of], prev + 2)
        return e_of, f_start, hits + 2 - f_start

    def segments_aligned(self, blob, starts, lengths):
        return segments_end_crlf(blob, starts, lengths)

    def rows_aligned(self, rows, lengths):
        return rows_end_crlf(rows, lengths)

    def payload_aligned(self, data: bytes) -> bool:
        return len(data) >= 2 and data.endswith(b"\r\n")

    def payload_single_frame(self, data: bytes) -> bool:
        return (
            len(data) >= 2
            and data.endswith(b"\r\n")
            and data.find(b"\r\n") == len(data) - 2
        )

    def segments_single_frame(self, blob, offs, lengths):
        offs = np.asarray(offs, np.int64)
        li = np.asarray(lengths, np.int64)
        aligned = segments_end_crlf(blob, offs, li)
        if not aligned.any():
            return aligned
        # Exactly one CR per entry => exactly one frame, ending at the
        # entry boundary.
        ends = offs + li
        crs = np.add.reduceat(
            (blob == 13).astype(np.int32), offs,
        ) if len(blob) else np.zeros(len(offs), np.int32)
        # reduceat sums to the NEXT start; the last segment sums to the
        # blob end — only exact for contiguous segments, so recompute
        # defensively for non-contiguous callers.
        contiguous = bool(
            len(offs) and (offs[1:] == ends[:-1]).all()
            and ends[-1] == len(blob) and offs[0] == 0
        )
        if not contiguous:
            crs = np.array(
                [int((blob[o : o + n] == 13).sum())
                 for o, n in zip(offs, li)],
                np.int32,
            )
        return aligned & (crs == 1)

    def rows_single_frame(self, rows, lengths):
        ok = rows_end_crlf(rows, lengths)
        return ok & ((rows == 13).sum(axis=1) == 1)


class LengthPrefixFraming(Framing):
    """Length-prefixed frames: total length = header + the integer at
    ``length_offset`` (+ extra).  DNS-over-TCP is header_bytes=2 with a
    2-byte big-endian prefix at offset 0; the cassandra v3/v4 frame is
    (9, 5) and kafka (4, 0) — registered once their engines' parser
    state goes arena-portable."""

    def __init__(self, kind: str, header_bytes: int, length_offset: int,
                 length_size: int = 4, big_endian: bool = True,
                 extra: int = 0, err_inject: bytes = b""):
        self.kind = kind
        self.header = int(header_bytes)
        self.err_inject = err_inject
        self._lo, self._ls = int(length_offset), int(length_size)
        self._be, self._extra = bool(big_endian), int(extra)
        self._reader = length_prefix_reader(
            header_bytes, length_offset, length_size, big_endian, extra
        )

    def frame_len_of(self, buf) -> int:
        """First frame's total length from host bytes, or -1 while the
        header is incomplete."""
        if len(buf) < self.header:
            return -1
        val = 0
        for k in range(self._ls):
            shift = (self._ls - 1 - k if self._be else k) * 8
            val |= buf[self._lo + k] << shift
        return self.header + val + self._extra

    def scan(self, stream, offs, ends):
        return scan_length_prefixed(stream, offs, ends, self._reader)

    def segments_aligned(self, blob, starts, lengths):
        starts = np.asarray(starts, np.int64)
        li = np.asarray(lengths, np.int64)
        n = len(li)
        ok = (li > 0) & (starts >= 0) & (starts + li <= len(blob))
        fe, _fs, fl = self.scan(
            blob, starts, starts + np.where(ok, li, 0)
        )
        consumed = np.zeros(n, np.int64)
        np.add.at(consumed, fe, fl)
        return ok & (consumed == li)

    def payload_aligned(self, data: bytes) -> bool:
        pos, n = 0, len(data)
        while pos < n:
            fl = self.frame_len_of(
                memoryview(data)[pos : pos + self.header]
            )
            if fl < 0 or pos + fl > n:
                return False
            pos += fl
        return n > 0

    def payload_single_frame(self, data: bytes) -> bool:
        return len(data) >= self.header and (
            self.frame_len_of(data) == len(data)
        )

    def segments_single_frame(self, blob, offs, lengths):
        offs = np.asarray(offs, np.int64)
        li = np.asarray(lengths, np.int64)
        ok = (li >= self.header) & (offs >= 0) & (offs + li <= len(blob))
        fl = self._reader(blob, np.where(ok, offs, 0),
                          np.where(ok, li, 0))
        return ok & (fl == li)


# The columnar lane's framing registry (see module docstring): kinds an
# engine may declare from ``reasm_spec()`` and actually ride the lane.
FRAMINGS: dict[str, Framing] = {
    FRAMING_CRLF: CrlfFraming(),
    FRAMING_DNS: LengthPrefixFraming(
        FRAMING_DNS, header_bytes=2, length_offset=0, length_size=2,
        err_inject=b"",  # DNS denies DROP with no inject
    ),
}


# --- the byte arena ------------------------------------------------------

class ByteArena:
    """One contiguous byte pool holding every reassembly carry.

    Per-conn state is three parallel slot columns (offset, length,
    dead) plus a direct-index conn→slot map; allocation is a bump
    pointer with gather-based compaction when the tail reaches the
    capacity (growing geometrically when the live set itself outgrows
    the pool).  Everything round-scale is vectorized; per-conn Python
    only happens at lane transitions (release/adopt) and close."""

    # Conn-id ceiling for the direct-index map (mirrors the service's
    # _TAB_MAX): larger ids simply never enter the columnar lane.
    MAP_MAX = 1 << 22

    def __init__(self, capacity: int = 1 << 20):
        self.buf = np.zeros(max(int(capacity), 1024), np.uint8)
        self._map = np.full(1024, -1, np.int32)
        n0 = 256
        self.s_off = np.zeros(n0, np.int64)
        self.s_len = np.zeros(n0, np.int64)
        self.s_conn = np.full(n0, -1, np.int64)
        self.s_dead = np.zeros(n0, np.uint8)
        self._n_slots = 0
        self._free: list[int] = []
        self._tail = 0
        self._live = 0
        self.compactions = 0
        self.grows = 0

    # -- conn→slot map ----------------------------------------------------

    def _ensure_map(self, max_cid: int) -> None:
        if max_cid < len(self._map):
            return
        size = len(self._map)
        while size <= max_cid:
            size *= 2
        grown = np.full(size, -1, np.int32)
        grown[: len(self._map)] = self._map
        self._map = grown

    def slots_for(self, cids: np.ndarray) -> np.ndarray:
        """Slot index per conn id (-1 = no slot).  Ids beyond MAP_MAX
        are reported slotless (they never enter the lane)."""
        cids = np.asarray(cids, np.int64)
        out = np.full(len(cids), -1, np.int32)
        ok = (cids >= 0) & (cids < len(self._map))
        out[ok] = self._map[cids[ok]]
        return out

    def has_slot(self, cids: np.ndarray) -> np.ndarray:
        return self.slots_for(cids) >= 0

    def _grow_slots(self, need: int) -> None:
        size = len(self.s_off)
        if self._n_slots + need <= size:
            return
        while size < self._n_slots + need:
            size *= 2
        for name, fill, dt in (("s_off", 0, np.int64),
                               ("s_len", 0, np.int64),
                               ("s_conn", -1, np.int64),
                               ("s_dead", 0, np.uint8)):
            arr = np.full(size, fill, dt)
            arr[: len(getattr(self, name))] = getattr(self, name)
            setattr(self, name, arr)

    def ensure_slots(self, cids: np.ndarray) -> np.ndarray:
        """Slot per conn, creating empty slots for new conns (one
        vectorized map scatter; the free list is consumed first)."""
        cids = np.asarray(cids, np.int64)
        if len(cids) and int(cids.max()) >= self.MAP_MAX:
            raise ValueError("conn id beyond arena map ceiling")
        if len(cids):
            self._ensure_map(int(cids.max()))
        slots = self._map[cids].astype(np.int32)
        missing = np.flatnonzero(slots < 0)
        if len(missing):
            new_ids = np.empty(len(missing), np.int32)
            n_free = min(len(self._free), len(missing))
            for k in range(n_free):  # free list is tiny; ids reused LIFO
                new_ids[k] = self._free.pop()
            fresh = len(missing) - n_free
            if fresh:
                self._grow_slots(fresh)
                new_ids[n_free:] = np.arange(
                    self._n_slots, self._n_slots + fresh, dtype=np.int32
                )
                self._n_slots += fresh
            mcids = cids[missing]
            self.s_off[new_ids] = 0
            self.s_len[new_ids] = 0
            self.s_conn[new_ids] = mcids
            self.s_dead[new_ids] = 0
            self._map[mcids] = new_ids
            slots[missing] = new_ids
        return slots

    # -- round-scale carry ops --------------------------------------------

    def carry(self, slots: np.ndarray):
        """(offsets, lengths) of the given slots' carries."""
        return self.s_off[slots], self.s_len[slots]

    def consume(self, slots: np.ndarray) -> None:
        """Mark the given slots' carries consumed (their bytes were
        gathered into a round stream)."""
        self._live -= int(self.s_len[slots].sum())
        self.s_len[slots] = 0

    def mark_dead(self, slots: np.ndarray) -> None:
        self.consume(slots)
        self.s_dead[slots] = 1

    def store(self, slots: np.ndarray, src, src_starts, lens) -> None:
        """Replace the given slots' carries with segments of ``src``
        (one ragged scatter into the pool)."""
        lens = np.asarray(lens, np.int64)
        total = int(lens.sum())
        if self._tail + total > len(self.buf):
            self._compact(total)
        dst = self._tail + np.concatenate(
            ([0], np.cumsum(lens))
        )[:-1].astype(np.int64)
        gather_segments(src, src_starts, lens, out=self.buf,
                        dst_starts=dst)
        self.s_off[slots] = dst
        # Replacement semantics: any un-consumed previous carry in
        # these slots stops being live (ingest consumes first; direct
        # replacement must not double-count).
        self._live -= int(self.s_len[slots].sum())
        self.s_len[slots] = lens
        self._tail += total
        self._live += total

    def _compact(self, need: int) -> None:
        used = np.flatnonzero(
            (self.s_conn[: self._n_slots] >= 0)
            & (self.s_len[: self._n_slots] > 0)
        )
        lens = self.s_len[used]
        live = int(lens.sum())
        cap = len(self.buf)
        while live + need > cap:
            cap *= 2
        data = self.buf[ragged_indices(self.s_off[used], lens)]
        if cap != len(self.buf):
            self.buf = np.zeros(cap, np.uint8)
            self.grows += 1
        self.buf[:live] = data
        self.s_off[used] = np.concatenate(
            ([0], np.cumsum(lens))
        )[:-1].astype(np.int64)
        self._tail = live
        self._live = live
        self.compactions += 1

    # -- lane transitions (per-conn; rare by design) ----------------------

    def release(self, conn_id: int) -> tuple[bytes, bool]:
        """Pull one conn out of the arena: (carry bytes, dead).  Used
        when a conn leaves the columnar lane (scalar routing, oracle
        demotion) — the bytes move into the scalar carry location."""
        if not (0 <= conn_id < len(self._map)):
            return b"", False
        slot = int(self._map[conn_id])
        if slot < 0:
            return b"", False
        off, ln = int(self.s_off[slot]), int(self.s_len[slot])
        dead = bool(self.s_dead[slot])
        data = self.buf[off : off + ln].tobytes()
        self._live -= ln
        self._map[conn_id] = -1
        self.s_conn[slot] = -1
        self.s_len[slot] = 0
        self.s_dead[slot] = 0
        self._free.append(slot)
        return data, dead

    def drop(self, conn_id: int) -> None:
        self.release(conn_id)

    def peek(self, conn_id: int) -> bytes:
        """Non-destructive read of a conn's columnar carry bytes.  The
        restart-handoff snapshot serializes residue IN PLACE: the conn
        must keep serving unchanged if the handoff is refused or the
        predecessor outlives the surrender attempt."""
        if not (0 <= conn_id < len(self._map)):
            return b""
        slot = int(self._map[conn_id])
        if slot < 0:
            return b""
        off, ln = int(self.s_off[slot]), int(self.s_len[slot])
        return self.buf[off : off + ln].tobytes()

    def has_residue(self, conn_id: int) -> bool:
        """True when this conn holds columnar carry state (bytes or the
        dead/overflowed latch) — the arena's contribution to the
        service's residual-dirty predicate."""
        if not (0 <= conn_id < len(self._map)):
            return False
        slot = int(self._map[conn_id])
        return slot >= 0 and (
            self.s_len[slot] > 0 or bool(self.s_dead[slot])
        )

    def status(self) -> dict:
        return {
            "capacity": len(self.buf),
            "tail": self._tail,
            "live_bytes": self._live,
            "slots": int(self._n_slots - len(self._free)),
            "compactions": self.compactions,
            "grows": self.grows,
        }


# --- one round's reassembly ----------------------------------------------

class ReasmRound:
    """Columnar result of one ingest: per-entry masks/offsets, the
    frame table, and the residue bookkeeping the finish half needs."""

    __slots__ = ("n", "conn_ids", "slots", "dead", "over", "live",
                 "over_total", "stream", "entry_off", "entry_end",
                 "f_entry", "f_start", "f_len", "n_frames", "res_len",
                 "more", "framing", "_gb", "_ge")

    def frame_count(self) -> int:
        return len(self.f_entry)


class Reassembler:
    """Round-scale reassembly over a :class:`ByteArena`, one framing
    per round group (``FRAMINGS``): CRLF for the r2d2/text-memcached
    class, length-prefixed for the DNS class — the service groups each
    round's entries by engine and hands every group its engine's
    declared framing."""

    def __init__(self, cap_per_conn: int = 1 << 20,
                 err_inject: bytes = b"ERROR\r\n",
                 inject_capacity: int = 1024,
                 arena_capacity: int = 1 << 20):
        self.arena = ByteArena(arena_capacity)
        self.cap = int(cap_per_conn)
        # Per-framing deny injects (``err_inject`` keeps the historic
        # ctor override for the CRLF lane's template).
        self._err = {k: f.err_inject for k, f in FRAMINGS.items()}
        self._err[FRAMING_CRLF] = bytes(err_inject)
        self.inject_capacity = int(inject_capacity)
        # Truncation templates per framing: enough repeats to cover the
        # per-entry inject cap, sliced per entry (matches the scalar
        # engine's byte-exact mid-pattern truncation at the capacity).
        self._err_tpls: dict[str, np.ndarray] = {}
        self.rounds = 0
        self.entries = 0
        self.frames = 0
        self.overflows = 0
        # Lane engagement per framing kind — the status surface the
        # non-CRLF smoke gates on (a silent scalar fallback reads 0).
        self.rounds_by_framing: dict[str, int] = {}

    def _tpl_for(self, kind: str) -> np.ndarray:
        tpl = self._err_tpls.get(kind)
        if tpl is None:
            err = np.frombuffer(self._err.get(kind, b""), np.uint8)
            reps = self.inject_capacity // max(len(err), 1) + 1
            tpl = np.tile(err, max(reps, 1)) if len(err) else err
            self._err_tpls[kind] = tpl
        return tpl

    def ingest(self, conn_ids, data_starts, data_lens,
               blob: np.ndarray,
               framing: Framing | None = None) -> ReasmRound:
        """Append one round's payloads to their conns' carries, find
        every completed frame under ``framing`` (default CRLF), and
        persist the residues — all as array passes.  ``conn_ids`` must
        be unique within the round (the service taints duplicate conns
        to the scalar lane)."""
        framing = framing or FRAMINGS[FRAMING_CRLF]
        conn_ids = np.asarray(conn_ids, np.int64)
        data_starts = np.asarray(data_starts, np.int64)
        data_lens = np.asarray(data_lens, np.int64)
        n = len(conn_ids)
        rnd = ReasmRound()
        rnd.n = n
        rnd.conn_ids = conn_ids
        arena = self.arena
        slots = arena.ensure_slots(conn_ids)
        rnd.slots = slots
        dead = arena.s_dead[slots].astype(bool)
        carry_off, carry_len = arena.carry(slots)
        carry_len = carry_len.copy()
        total = carry_len + data_lens
        over = (~dead) & (total > self.cap) if self.cap else (
            np.zeros(n, bool)
        )
        live = ~(dead | over)
        rnd.dead = dead
        rnd.over = over
        rnd.live = live
        rnd.over_total = np.where(over, total, 0)
        if over.any():
            arena.mark_dead(slots[over])
            self.overflows += int(over.sum())
        # Round stream = [carry_i][payload_i] per live entry.
        l_cl = np.where(live, carry_len, 0)
        l_dl = np.where(live, data_lens, 0)
        tot = l_cl + l_dl
        entry_end = np.cumsum(tot)
        entry_off = entry_end - tot
        stream = np.empty(int(entry_end[-1]) if n else 0, np.uint8)
        gather_segments(arena.buf, carry_off, l_cl, out=stream,
                        dst_starts=entry_off)
        gather_segments(blob, data_starts, l_dl, out=stream,
                        dst_starts=entry_off + l_cl)
        rnd.stream = stream
        rnd.entry_off = entry_off
        rnd.entry_end = entry_end
        rnd.framing = framing
        # Frame boundaries + per-entry residue, columnar.  The framing
        # contract (Framing.scan): frames sorted by entry then stream
        # order and contiguous from each entry's offset, so the residue
        # of a framed entry starts where its LAST frame ends.
        e_of, f_start, f_len = framing.scan(stream, entry_off, entry_end)
        nf = len(e_of)
        first = np.ones(nf, bool)
        if nf:
            first[1:] = e_of[1:] != e_of[:-1]
        rnd.f_entry = e_of
        rnd.f_start = f_start
        rnd.f_len = f_len
        rnd.n_frames = np.bincount(e_of, minlength=n).astype(np.int64)
        res_start = entry_off.copy()
        gb = np.flatnonzero(first)
        ge = np.concatenate((gb[1:], [nf])) - 1 if nf else gb
        rnd._gb = gb
        rnd._ge = ge
        if nf:
            res_start[e_of[gb]] = f_start[ge] + f_len[ge]
        res_len = entry_end - res_start
        rnd.res_len = res_len
        rnd.more = (rnd.n_frames > 0) | (res_len > 0)
        # TRANSACTIONAL commit point: everything above (including the
        # framing scan, the raise-capable pluggable hook) ran on the
        # round-local stream without touching the live carries, so a
        # scan crash leaves every carry intact and the service can
        # exit the whole group to the scalar rung with zero byte
        # loss.  Only now are the consumed carries retired and the
        # residues stored back.
        arena.consume(slots[live])
        arena.store(slots[live], stream, res_start[live], res_len[live])
        self.rounds += 1
        self.entries += n
        self.frames += nf
        self.rounds_by_framing[framing.kind] = (
            self.rounds_by_framing.get(framing.kind, 0) + 1
        )
        return rnd

    # -- device-batch packing ---------------------------------------------

    def pack_buckets(self, rnd: ReasmRound, base_width: int,
                     min_bucket: int, remotes_entry: np.ndarray) -> list:
        """Group the round's frames into the SAME power-of-two
        (bucket, width) shapes the scalar async path uses, packed with
        ragged scatters.  Returns ``[(frame_idx, data, lengths,
        remotes)]`` with widths ascending and frames in stream order —
        bit-identical model inputs to the scalar `_issue_slow_async`."""
        msg_len = rnd.f_len
        nf = len(msg_len)
        if nf == 0:
            return []
        ratio = msg_len / float(base_width)
        exps = np.ceil(np.log2(np.maximum(ratio, 1.0))).astype(np.int64)
        widths = base_width << exps
        out = []
        for wv in np.unique(widths):
            fi = np.flatnonzero(widths == wv)
            nb = len(fi)
            f_pad = min_bucket
            while f_pad < nb:
                f_pad *= 2
            data = np.zeros((f_pad, int(wv)), np.uint8)
            dst = np.arange(nb, dtype=np.int64) * int(wv)
            gather_segments(rnd.stream, rnd.f_start[fi], msg_len[fi],
                            out=data.reshape(-1), dst_starts=dst)
            lengths = np.zeros(f_pad, np.int32)
            lengths[:nb] = msg_len[fi]
            remotes = np.zeros(f_pad, np.int32)
            remotes[:nb] = remotes_entry[rnd.f_entry[fi]]
            out.append((fi, data, lengths, remotes))  # lint: disable=R7 -- per width BUCKET (a handful per round), not per entry
        return out

    # -- finish half: columnar ops / injects / records --------------------

    def assemble(self, rnd: ReasmRound, allow_frame: np.ndarray):
        """Render the round's per-entry ops + reply injects as columnar
        arrays, op-for-op identical to the scalar
        ``settle_entry``/``_overflow`` contract:

        - judged frame → ``(PASS msg_len)`` or ``(DROP msg_len)`` with
          the framing's deny inject (``ERROR\\r\\n`` for CRLF, nothing
          for DNS) appended to the reply inject (truncated at the
          per-entry inject capacity);
        - trailing ``(MORE 1)`` when the entry completed frames or left
          residue;
        - cap overflow → ``(DROP carried+incoming), (ERROR code)``,
          flow dead;
        - entry on a dead flow → ``(ERROR code)``.

        Returns ``(op_counts i64[n], ops FILTER_OP[sum], inj_reply_lens
        i64[n], inj_blob u8[sum], n_denied i64[n])``."""
        from . import wire

        n = rnd.n
        op_counts = np.zeros(n, np.int64)
        op_counts[rnd.live] = (
            rnd.n_frames[rnd.live] + rnd.more[rnd.live]
        )
        op_counts[rnd.over] = 2
        op_counts[rnd.dead] = 1
        total_ops = int(op_counts.sum())
        op_off = np.concatenate(
            ([0], np.cumsum(op_counts))
        )[:-1].astype(np.int64)
        ops = np.zeros(total_ops, wire.FILTER_OP)
        nf = len(rnd.f_entry)
        if nf:
            counts = np.diff(np.concatenate((rnd._gb, [nf])))
            ordinal = np.arange(nf, dtype=np.int64) - np.repeat(
                rnd._gb, counts
            )
            fpos = op_off[rnd.f_entry] + ordinal
            ops["op"][fpos] = np.where(allow_frame, OP_PASS, OP_DROP)
            ops["n_bytes"][fpos] = rnd.f_len
        m_idx = np.flatnonzero(rnd.live & rnd.more)
        if len(m_idx):
            mpos = op_off[m_idx] + rnd.n_frames[m_idx]
            ops["op"][mpos] = OP_MORE
            ops["n_bytes"][mpos] = 1
        o_idx = np.flatnonzero(rnd.over)
        if len(o_idx):
            ops["op"][op_off[o_idx]] = OP_DROP
            ops["n_bytes"][op_off[o_idx]] = rnd.over_total[o_idx]
            ops["op"][op_off[o_idx] + 1] = OP_ERROR
            ops["n_bytes"][op_off[o_idx] + 1] = ERR_FRAME_LEN
        d_idx = np.flatnonzero(rnd.dead)
        if len(d_idx):
            ops["op"][op_off[d_idx]] = OP_ERROR
            ops["n_bytes"][op_off[d_idx]] = ERR_FRAME_LEN
        # Reply injects: one framing deny-inject per denied frame,
        # byte-exact truncation at the per-entry capacity.
        n_denied = np.bincount(
            rnd.f_entry[~allow_frame] if nf else np.empty(0, np.int64),
            minlength=n,
        ).astype(np.int64)
        kind = getattr(rnd.framing, "kind", FRAMING_CRLF)
        err_tpl = self._tpl_for(kind)
        err_n = len(self._err.get(kind, b""))
        inj_len = np.minimum(n_denied * err_n, self.inject_capacity)
        total_inj = int(inj_len.sum())
        inj_blob = np.empty(total_inj, np.uint8)
        inj_off = np.concatenate(
            ([0], np.cumsum(inj_len))
        )[:-1].astype(np.int64)
        gather_segments(err_tpl, np.zeros(n, np.int64), inj_len,
                        out=inj_blob, dst_starts=inj_off)
        return op_counts, ops, inj_len, inj_blob, n_denied

    def last_rules(self, rnd: ReasmRound,
                   rule_frame: np.ndarray) -> np.ndarray:
        """Per-entry rule of the LAST judged frame (-1 where the entry
        completed no frame) — the columnar analog of the scalar
        ``FlowState.last_rule_id`` stamp `_engine_rule_kind` reads."""
        out = np.full(rnd.n, -1, np.int32)
        nf = len(rnd.f_entry)
        if nf:
            out[rnd.f_entry[rnd._gb]] = rule_frame[rnd._ge]
        return out

    def entry_ops(self, rnd: ReasmRound, op_counts, ops, inj_len,
                  inj_blob, i: int):
        """Materialize ONE entry's response tuple (scalar-shape
        fallback for op-capacity splitting and mixed-lane merges)."""
        off = int(np.sum(op_counts[:i]))
        cnt = int(op_counts[i])
        io = int(np.sum(inj_len[:i]))
        il = int(inj_len[i])
        return (
            [(int(o["op"]), int(o["n_bytes"]))
             for o in ops[off : off + cnt]],
            inj_blob[io : io + il].tobytes(),
        )

    def status(self) -> dict:
        return {
            "rounds": self.rounds,
            "rounds_by_framing": dict(self.rounds_by_framing),
            "entries": self.entries,
            "frames": self.frames,
            "overflows": self.overflows,
            "arena": self.arena.status(),
        }
